"""Fused fit-statistics engine tests (fitstats.py — the
SequenceAggregators analog).

Parity discipline: for every opted-in estimator the fused layer pass
must produce a model whose state is BIT-IDENTICAL to the sequential
``fit_columns`` path (the host execution tier computes the exact same
numpy expressions on the same compressed arrays). The device tier is a
numerically-close twin (Chan-combined chunk folds) behind the same
bandwidth gate as layer fusion, with its own chunked-vs-one-shot parity
and one-program-per-layer-shape compile guard.
"""
import numpy as np
import pytest

import transmogrifai_tpu.fitstats as fitstats
import transmogrifai_tpu.workflow as wf
from transmogrifai_tpu import (ColumnStore, FeatureBuilder, Workflow,
                               column_from_values, telemetry)
from transmogrifai_tpu.columns import NumericColumn
from transmogrifai_tpu.dsl import FillMissingWithMean, ScalarNormalizer
from transmogrifai_tpu.fitstats import LayerStatsPlan, StatRequest
from transmogrifai_tpu.ops.numeric import (BinaryVectorizer,
                                           IntegralVectorizer,
                                           NumericBucketizer,
                                           RealVectorizer)
from transmogrifai_tpu.ops.onehot import OneHotVectorizer, SetVectorizer
from transmogrifai_tpu.ops.sanity_checker import SanityChecker
from transmogrifai_tpu.ops.scalers import OpScalarStandardScaler
from transmogrifai_tpu.stages.base import Estimator, FittedModel
from transmogrifai_tpu.types import feature_types as ft


@pytest.fixture(autouse=True)
def _host_gate(monkeypatch):
    """Pin the bandwidth gate LOW: the fused pass's host tier runs (the
    bit-exact one); device-tier tests force device=True explicitly."""
    monkeypatch.setattr(wf, "_DEVICE_BW_MBPS", 1.0)
    yield


@pytest.fixture
def store(rng):
    n = 400
    cols = {}
    for j in range(3):
        v = rng.normal(size=n) * 10 ** j + j
        vals = [None if rng.random() < 0.15 else float(x) for x in v]
        cols[f"x{j}"] = column_from_values(ft.Real, vals)
    ints = [None if rng.random() < 0.2 else int(rng.integers(0, 5))
            for _ in range(n)]
    cols["i0"] = column_from_values(ft.Integral, ints)
    bools = [None if rng.random() < 0.3 else bool(rng.integers(0, 2))
             for _ in range(n)]
    cols["b0"] = column_from_values(ft.Binary, bools)
    cats = ["a", "b", "c", "d", None]
    cols["cat"] = column_from_values(
        ft.PickList, [cats[int(rng.integers(0, 5))] for _ in range(n)])
    sets = [set(np.random.default_rng(i).choice(
        ["u", "v", "w"], size=i % 3).tolist()) for i in range(n)]
    cols["set0"] = column_from_values(ft.MultiPickList, sets)
    return ColumnStore(cols, n)


def _fused_fit(stage, store, device=False):
    reqs = stage.stat_requests(store)
    assert reqs is not None
    plan = LayerStatsPlan(list(reqs), n_stages=1)
    stats = plan.run(store, device=device)
    return stage.fit(store, stats=stats)


def _feat(name, ftype=ft.Real):
    return getattr(FeatureBuilder, ftype.__name__)(name) \
        .from_column().as_predictor()


def _assert_state_identical(m1, m2):
    s1, s2 = m1.get_model_state(), m2.get_model_state()
    assert repr(sorted(s1.items())) == repr(sorted(s2.items())), (s1, s2)


def test_fused_parity_bit_identical_every_stage(store):
    """Every opted-in estimator: fused (host tier) == sequential,
    bit for bit."""
    cases = []
    for st in (FillMissingWithMean(), ScalarNormalizer(),
               OpScalarStandardScaler()):
        st.set_input(_feat("x1"))
        cases.append(st)
    rv = RealVectorizer()
    rv.set_input(_feat("x0"), _feat("x1"), _feat("x2"))
    cases.append(rv)
    iv = IntegralVectorizer()
    iv.set_input(_feat("i0", ft.Integral))
    cases.append(iv)
    bv = BinaryVectorizer()
    bv.set_input(_feat("b0", ft.Binary))
    cases.append(bv)
    nb = NumericBucketizer(num_buckets=4)
    nb.set_input(_feat("x2"))
    cases.append(nb)
    oh = OneHotVectorizer(top_k=3, min_support=1)
    oh.set_input(_feat("cat", ft.PickList))
    cases.append(oh)
    sv = SetVectorizer(top_k=2, min_support=1)
    sv.set_input(_feat("set0", ft.MultiPickList))
    cases.append(sv)

    for stage in cases:
        seq = stage.fit(store)
        fused = _fused_fit(stage, store)
        _assert_state_identical(seq, fused)


def test_fused_parity_sanity_checker(rng):
    """SanityChecker: fused and sequential fits share one compute path —
    identical keep indices AND identical summary statistics."""
    n = 300
    y = rng.integers(0, 2, n).astype(float)
    X = rng.normal(size=(n, 5)).astype(np.float32)
    X[:, 0] = y + rng.normal(size=n) * 1e-4       # leaky column
    X[:, 1] = 0.0                                 # zero variance
    from transmogrifai_tpu.columns import VectorColumn
    from transmogrifai_tpu.vector_metadata import (VectorColumnMetadata,
                                                   VectorMetadata)
    meta = VectorMetadata("vec", [
        VectorColumnMetadata(parent_feature_name=f"f{i}",
                             parent_feature_type="Real")
        for i in range(5)])
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "vec": VectorColumn(ft.OPVector, X, meta),
    })
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    vecf = FeatureBuilder.OPVector("vec").from_column().as_predictor()

    checker = SanityChecker(remove_bad_features=True,
                            remove_feature_group=False)
    checker.set_input(label, vecf)
    seq = checker.fit(store)
    fused = _fused_fit(checker, store)
    assert seq.keep_indices == fused.keep_indices
    assert repr(seq.summary_.to_json()) == repr(fused.summary_.to_json())


class _NoStatsEstimator(Estimator):
    """Minimal estimator that does NOT opt in (stat_requests → None)."""

    operation_name = "noStats"
    output_type = ft.RealNN

    @property
    def input_spec(self):
        from transmogrifai_tpu.stages.base import FixedArity
        return FixedArity(ft.OPNumeric)

    def fit_columns(self, store):
        from transmogrifai_tpu.dsl import FillMissingWithMeanModel
        col = store[self.input_features[0].name]
        return FillMissingWithMeanModel(mean=float(
            col.values[col.mask].mean()))


def _layer_workflow(store, n_fill=3, with_pivot=True):
    outs = []
    for j in range(n_fill):
        outs.append(_feat(f"x{j}").fill_missing_with_mean())
    if with_pivot:
        outs.append(_feat("cat", ft.PickList).pivot(top_k=3, min_support=1))
    return Workflow().set_input_store(store).set_result_features(*outs)


def test_layer_with_three_estimators_scans_once(store, monkeypatch):
    """ISSUE acceptance: a layer with ≥3 opted-in estimators scans the
    train store EXACTLY once — fit_columns never runs (the per-stage
    scan path), and fitstats.bytes_scanned equals one visit per unique
    input column."""
    telemetry.reset()
    telemetry.enable()
    fitstats.reset_fitstats_stats()

    def _boom(self, store):
        raise AssertionError("sequential fit_columns ran on the fused path")
    monkeypatch.setattr(FillMissingWithMean, "fit_columns", _boom)
    monkeypatch.setattr(OneHotVectorizer, "fit_columns", _boom)
    try:
        model = _layer_workflow(store).train()
        expected = 0
        for name in ("x0", "x1", "x2"):
            col = store[name]
            expected += col.values.nbytes + col.mask.nbytes
        expected += store["cat"].values.nbytes   # object ptrs; no mask attr
        assert telemetry.counter(
            "fitstats.bytes_scanned").value == expected
        assert telemetry.counter("fitstats.passes_saved").value == 3
    finally:
        telemetry.disable()
        telemetry.reset()

    tallies = fitstats.fitstats_stats()
    assert tallies["layers_fused"] == 1
    assert tallies["passes_saved"] == 3       # 4 estimators, one pass
    assert tallies["bytes_scanned"] == expected
    assert len(model.fitted_stages) == 4
    for m in model.fitted_stages.values():
        assert m.get_model_state()            # real fitted state


def test_fused_counters_reach_telemetry(store):
    telemetry.reset()
    telemetry.enable()
    try:
        collector = telemetry.add_listener(telemetry.CollectingRunListener())
        _layer_workflow(store).train()
        assert telemetry.counter("fitstats.layers_fused").value == 1
        assert telemetry.counter("fitstats.passes_saved").value == 3
        assert telemetry.counter("fitstats.bytes_scanned").value > 0
        s = collector.summary()
        assert s["statsPasses"] == 1 and s["fitPassesSaved"] == 3
        names = [e["name"] for e in telemetry.trace_events()
                 if e.get("ph") == "X"]
        assert "fit:stats_pass" in names
    finally:
        telemetry.disable()
        telemetry.reset()


def test_fallback_layer_without_opted_estimators(store):
    """A layer whose estimators don't opt in fits sequentially — no
    fused pass recorded, models still correct."""
    fitstats.reset_fitstats_stats()
    st = _NoStatsEstimator()
    st.set_input(_feat("x0"))
    out = st.get_output()
    model = Workflow().set_input_store(store) \
        .set_result_features(out).train()
    assert fitstats.fitstats_stats()["layers_fused"] == 0
    assert len(model.fitted_stages) == 1


def test_single_opted_estimator_stays_sequential(store):
    """One opted-in estimator saves no pass → no fused plan runs
    (FITSTATS_MIN_STAGES)."""
    fitstats.reset_fitstats_stats()
    _layer_workflow(store, n_fill=1, with_pivot=False).train()
    assert fitstats.fitstats_stats()["layers_fused"] == 0


def test_disabled_flag_restores_sequential(store, monkeypatch):
    monkeypatch.setattr(fitstats, "FITSTATS_ENABLED", False)
    fitstats.reset_fitstats_stats()
    model = _layer_workflow(store).train()
    assert fitstats.fitstats_stats()["layers_fused"] == 0
    assert len(model.fitted_stages) == 4


def test_chunked_vs_oneshot_device_parity(store, monkeypatch):
    """The device fold's Chan combine: tiny chunks == one chunk (counts
    and extrema exactly, f-moments to f64 tolerance)."""
    reqs = []
    for j in range(3):
        name = f"x{j}"
        reqs += [StatRequest("count", name), StatRequest("mean", name),
                 StatRequest("variance", name), StatRequest("std", name),
                 StatRequest("std", name, params=(1,)),
                 StatRequest("min", name), StatRequest("max", name)]
    plan = LayerStatsPlan(reqs, n_stages=3)
    oneshot = plan.run(store, device=True)
    monkeypatch.setattr(fitstats, "FITSTATS_CHUNK_ROWS", 128)
    # force the pow2 floor down so chunking actually happens at n=400
    monkeypatch.setattr(fitstats, "_chunk_rows", lambda n: 128)
    chunked = plan.run(store, device=True)
    for r in plan.requests:
        a, b = oneshot.for_request(r), chunked.for_request(r)
        if r.kind in ("count", "min", "max"):
            assert a == b, (r, a, b)
        else:
            assert np.isclose(a, b, rtol=1e-10), (r, a, b)


def test_device_vs_host_close(store):
    """Device tier tracks the bit-exact host tier to f64 tolerance."""
    reqs = [StatRequest(k, "x2") for k in
            ("count", "mean", "variance", "std", "min", "max")]
    plan = LayerStatsPlan(reqs, n_stages=2)
    host = plan.run(store, device=False)
    dev = plan.run(store, device=True)
    for r in plan.requests:
        a, b = host.for_request(r), dev.for_request(r)
        if r.kind in ("count", "min", "max"):
            assert a == b
        else:
            assert np.isclose(a, b, rtol=1e-9), (r.kind, a, b)


def test_compile_count_one_program_per_layer_shape(rng, monkeypatch):
    """Mirror of the scoring engine's bucket-budget guard: distinct row
    counts within one chunk shape share ONE compiled fold program; a
    different column width adds exactly one more."""
    monkeypatch.setattr(fitstats, "_chunk_rows", lambda n: 512)
    fitstats._PROGRAM_CACHE.clear()
    fitstats.reset_fitstats_stats()

    def _store(n, k):
        cols = {f"c{j}": column_from_values(
            ft.Real, list(rng.normal(size=n))) for j in range(k)}
        return ColumnStore(cols, n)

    def _plan(k):
        return LayerStatsPlan(
            [StatRequest("mean", f"c{j}") for j in range(k)], n_stages=k)

    for n in (100, 300, 500, 512):
        _plan(2).run(_store(n, 2), device=True)
    assert fitstats.fitstats_stats()["programs_compiled"] == 1
    _plan(3).run(_store(200, 3), device=True)
    assert fitstats.fitstats_stats()["programs_compiled"] == 2


def test_scalar_normalizer_f64_at_1e7_scale(rng):
    """Satellite regression: 1e7-scale values in an f32-BACKED column
    must normalize without fp32 mean/variance skew — fit accumulates in
    f64 on both the sequential and the fused path."""
    n = 20_000
    base = 1e7
    noise = rng.normal(size=n)
    vals32 = (base + noise).astype(np.float32)
    col = NumericColumn(ft.Real, vals32, np.ones(n, bool))
    store = ColumnStore({"big": col}, n)

    stage = ScalarNormalizer()
    stage.set_input(_feat("big"))
    seq = stage.fit(store)
    fused = _fused_fit(stage, store)
    _assert_state_identical(seq, fused)

    # reference: exact f64 two-pass over the (f32-rounded) values
    ref = vals32.astype(np.float64)
    assert seq.mean == pytest.approx(float(ref.mean()), rel=1e-12)
    assert seq.std == pytest.approx(float(ref.std()), rel=1e-12)
    # the std of unit-ish noise survives (an fp32 accumulation collapses
    # it: eps(1e7) in f32 is ~1, the same order as the signal)
    assert 0.5 < seq.std < 2.0
    out = seq.transform(store)[seq.output_name]
    assert abs(float(out.values.mean())) < 0.05
    assert float(out.values.std()) == pytest.approx(1.0, rel=0.05)

    # fused DEVICE tier too (f64 under the x64 test config)
    dev = _fused_fit(stage, store, device=True)
    assert dev.std == pytest.approx(seq.std, rel=1e-9)
    assert dev.mean == pytest.approx(seq.mean, rel=1e-12)


def test_stats_value_mismatch_raises(store):
    plan = LayerStatsPlan([StatRequest("mean", "x0")], n_stages=1)
    stats = plan.run(store)
    with pytest.raises(KeyError, match="not computed"):
        stats.value("mean", "x1")


def test_shared_request_dedup(store):
    """Two stages needing the same column's counts share one request."""
    a = OneHotVectorizer(top_k=2, min_support=1)
    a.set_input(_feat("cat", ft.PickList))
    b = OneHotVectorizer(top_k=4, min_support=1)
    b.set_input(_feat("cat", ft.PickList))
    plan = LayerStatsPlan(list(a.stat_requests(store))
                          + list(b.stat_requests(store)), n_stages=2)
    assert plan.n_requests == 1
    stats = plan.run(store)
    ma = a.fit(store, stats=stats)
    mb = b.fit(store, stats=stats)
    assert ma.vocabs != mb.vocabs       # per-stage top-K cut still applies
    _assert_state_identical(ma, a.fit(store))
    _assert_state_identical(mb, b.fit(store))


def test_warm_started_stages_excluded_from_plan(store):
    """Warm-started estimators must not be re-scanned OR re-finalized:
    a layer with 3 fills where 2 are warm leaves only 1 opted-in stage
    → below FITSTATS_MIN_STAGES, sequential."""
    model = _layer_workflow(store, with_pivot=False).train()
    fitstats.reset_fitstats_stats()
    wf2 = _layer_workflow(store, with_pivot=False)
    # reuse the SAME features so uids match
    wf2.result_features = model.result_features
    wf2.set_input_store(store).with_model_stages(model)
    model2 = wf2.train()
    assert fitstats.fitstats_stats()["layers_fused"] == 0
    for uid in model.fitted_stages:
        _assert_state_identical(model.fitted_stages[uid],
                                model2.fitted_stages[uid])


# -- satellite coverage ----------------------------------------------------


def test_runner_compile_cache_dir(rng, tmp_path):
    """customParams.compileCacheDir wires jax's persistent compilation
    cache and its presence is stamped into the metrics doc."""
    import jax

    from transmogrifai_tpu.runner import OpParams, OpWorkflowRunner, RunType

    y = rng.integers(0, 2, 120).astype(float)
    x = rng.normal(size=120) + y
    records = [{"label": float(y[i]), "x": float(x[i])} for i in range(120)]

    label = FeatureBuilder.RealNN("label").from_column().as_response()
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    out = fx.fill_missing_with_mean()
    flow = Workflow().set_result_features(out)

    class _Reader:
        def read_records(self):
            return list(records)

    cache = tmp_path / "xla-cache"
    old = jax.config.jax_compilation_cache_dir
    try:
        runner = OpWorkflowRunner(flow, training_reader=_Reader())
        params = OpParams(
            metrics_location=str(tmp_path / "metrics.json"),
            custom_params={"compileCacheDir": str(cache)})
        res = runner.run(RunType.TRAIN, params)
        assert res.metrics["compileCacheDir"] == str(cache)
        assert cache.is_dir()
        assert jax.config.jax_compilation_cache_dir == str(cache)
        # absent config → stamped None (presence is always recorded)
        res2 = OpWorkflowRunner(flow, training_reader=_Reader()).run(
            RunType.TRAIN, OpParams())
        assert res2.metrics["compileCacheDir"] is None
    finally:
        jax.config.update("jax_compilation_cache_dir", old)


def test_op_app_compile_cache_flag(rng, tmp_path):
    import jax

    from transmogrifai_tpu.runner import OpApp, OpWorkflowRunner

    y = rng.integers(0, 2, 60).astype(float)
    records = [{"label": float(y[i]), "x": float(i)} for i in range(60)]
    fx = FeatureBuilder.Real("x").from_column().as_predictor()
    flow = Workflow().set_result_features(fx.fill_missing_with_mean())

    class _Reader:
        def read_records(self):
            return list(records)

    class _App(OpApp):
        def runner(self, params):
            return OpWorkflowRunner(flow, training_reader=_Reader())

    cache = tmp_path / "cli-cache"
    old = jax.config.jax_compilation_cache_dir
    try:
        out = _App().main(["--run-type", "Train", "--quiet",
                           "--compile-cache-dir", str(cache)])
        assert out.metrics["compileCacheDir"] == str(cache)
        assert cache.is_dir()
    finally:
        jax.config.update("jax_compilation_cache_dir", old)


def test_hoisted_copy_import():
    """workflow's warm-start copy import lives at module scope now."""
    import transmogrifai_tpu.workflow as w
    assert hasattr(w, "_copy")
    import inspect
    src = inspect.getsource(w.Workflow._fit_layer)
    assert "import copy" not in src


def test_mesh_constructions_tally_stays_flat(store):
    """PR 6 satellite: the device stats pass must reuse the caller's /
    process-default mesh — repeated passes build ZERO new meshes, and
    fitstats_stats() surfaces the count so a regression back to a
    throwaway mesh-per-pass is visible in every bench doc."""
    from transmogrifai_tpu.parallel.mesh import process_default_mesh

    process_default_mesh()                 # ensure the cached build
    c0 = fitstats.fitstats_stats()["mesh_constructions"]
    plan = LayerStatsPlan([StatRequest("mean", "x0"),
                           StatRequest("variance", "x1")], n_stages=2)
    plan.run(store, device=True)
    plan.run(store, device=True)
    assert fitstats.fitstats_stats()["mesh_constructions"] == c0


# ---------------------------------------------------------------------------
# PR 16 tentpole (a): out-of-core streaming fold — bit-parity with the
# materialized device pass
# ---------------------------------------------------------------------------


def _batch_stores(store, names, sizes):
    """Slice `store` into consecutive batch ColumnStores of the given
    sizes (the shape a DirectoryStreamReader's decoded batches take)."""
    out, off = [], 0
    for m in sizes:
        idx = np.arange(off, off + m)
        out.append(ColumnStore({nm: store[nm].take(idx) for nm in names},
                               m))
        off += m
    assert off == store.n_rows
    return out


def _materialized_states(store, names, mesh):
    so = {}
    fitstats._device_moment_bundles(
        store, {nm: {"mean": [()]} for nm in names}, mesh=mesh,
        states_out=so)
    return so


@pytest.mark.parametrize("mesh", [False, None])
def test_streaming_fold_bit_identical_to_materialized(store, mesh):
    """StreamingMomentFold over reader-shaped batches == the
    materialized ``_device_moment_bundles`` pass over the same rows,
    bit for bit — sharded (process-default mesh) and unsharded."""
    names = ["x0", "x1", "x2"]
    want = _materialized_states(store, names, mesh)

    fold = fitstats.StreamingMomentFold(names, mesh=mesh)
    for b in _batch_stores(store, names, [150, 150, 100]):
        fold.update(b)
    got = fold.finalize()

    assert fold.n_rows == store.n_rows
    assert sorted(got) == sorted(want)
    for nm in names:
        g, w = got[nm], want[nm]
        assert (g.count, g.mean, g.m2, g.min, g.max) \
            == (w.count, w.mean, w.m2, w.min, w.max), nm


def test_streaming_fold_multi_chunk_and_batch_invariant(store,
                                                        monkeypatch):
    """Batch boundaries never leak into the result: any re-batching of
    the stream Chan-combines to the same partials — including when the
    stream spans MULTIPLE fixed-shape chunks (chunk floor shrunk so 400
    rows cut into 128-row interior chunks + a padded tail, on both the
    streamed and materialized paths)."""
    monkeypatch.setattr(fitstats, "FITSTATS_CHUNK_ROWS", 128)
    names = ["x0", "x2"]
    want = _materialized_states(store, names, False)

    for sizes in ([400], [128, 128, 128, 16], [37] * 10 + [30],
                  [1] * 5 + [395]):
        fold = fitstats.StreamingMomentFold(names, mesh=False)
        for b in _batch_stores(store, names, sizes):
            fold.update(b)
        got = fold.finalize()
        for nm in names:
            g, w = got[nm], want[nm]
            assert (g.count, g.mean, g.m2, g.min, g.max) \
                == (w.count, w.mean, w.m2, w.min, w.max), (nm, sizes)


def test_streamed_stats_injected_into_fused_pass(store):
    """A workflow-carried full-stream SufficientStats overrides the
    (subsample) store's own numbers in the fused pass: the moment stats
    a stage fits against reflect ALL streamed rows."""
    full = _materialized_states(store, ["x1"], False)["x1"]
    fake = fitstats.SufficientStats(full.count * 2, full.mean + 1.0,
                                    full.m2, full.min - 5.0,
                                    full.max + 5.0)
    plan = LayerStatsPlan([StatRequest("mean", "x1"),
                           StatRequest("count", "x1"),
                           StatRequest("min", "x1")], n_stages=1)
    stats = plan.run(store, device=True, stream_state={"x1": fake})
    assert stats.value("mean", "x1") == fake.finalize("mean")
    assert stats.value("count", "x1") == int(fake.count)
    assert stats.value("min", "x1") == fake.min


def test_streamed_fit_bit_identical_per_stage_family(store):
    """Per opted-in moment-family estimator: fitting from the
    streaming fold's full-stream states == fitting from the
    materialized device pass, bit for bit — the ISSUE 16 acceptance
    contract at the stage level, not just the fold level."""
    from transmogrifai_tpu.models import _treefit  # noqa: F401 (env parity)

    cases = []
    for st in (FillMissingWithMean(), ScalarNormalizer(),
               OpScalarStandardScaler()):
        st.set_input(_feat("x1"))
        cases.append(st)

    streamed = fitstats.StreamingMomentFold(["x1"], mesh=False)
    for b in _batch_stores(store, ["x1"], [123, 277]):
        streamed.update(b)
    states = streamed.finalize()

    for stage in cases:
        reqs = list(stage.stat_requests(store))
        plan = LayerStatsPlan(reqs, n_stages=1)
        mat = stage.fit(store, stats=plan.run(store, device=True,
                                              mesh=False))
        stream = stage.fit(store, stats=plan.run(
            store, device=True, mesh=False, stream_state=states))
        _assert_state_identical(mat, stream)
