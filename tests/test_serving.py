"""StableHLO export serving tests (OpWorkflowModelLocal / MLeap analog)."""
import numpy as np
import pytest

from transmogrifai_tpu import ColumnStore, FeatureBuilder, Workflow, column_from_values
from transmogrifai_tpu.models.linear import LogisticRegressionFamily
from transmogrifai_tpu.models.selector import BinaryClassificationModelSelector
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.serving import export_prediction_fn, load_prediction_fn
from transmogrifai_tpu.types import feature_types as ft


def _fitted(rng, families=None, n=200):
    y = rng.integers(0, 2, n).astype(float)
    x1 = rng.normal(size=n) + y
    x2 = rng.normal(size=n)
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "x1": column_from_values(ft.Real, list(x1)),
        "x2": column_from_values(ft.Real, list(x2)),
    })
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    f1 = FeatureBuilder.Real("x1").from_column().as_predictor()
    f2 = FeatureBuilder.Real("x2").from_column().as_predictor()
    vec = transmogrify([f1, f2])
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=families or [LogisticRegressionFamily()],
        splitter=None, seed=9)
    pred = label.transform_with(selector, vec)
    model = Workflow().set_input_store(store).set_result_features(pred).train()
    return model, store, pred


def test_export_roundtrip_matches_predict(rng, tmp_path):
    model, store, pred = _fitted(rng)
    meta = export_prediction_fn(model, str(tmp_path))
    d = meta["featureDim"]

    fn = load_prediction_fn(str(tmp_path))
    # batch-polymorphic: different request sizes, one artifact
    for n in (1, 7, 33):
        X = rng.normal(size=(n, d)).astype(np.float32)
        out = fn(X)
        assert out["prediction"].shape == (n,)
        assert out["probability"].shape[0] == n
        direct = model.stage_of(pred).predict_arrays(X.astype(np.float64))
        np.testing.assert_allclose(out["prediction"], direct[0], rtol=1e-4)
        np.testing.assert_allclose(out["probability"], direct[2],
                                   rtol=1e-4, atol=1e-5)


def test_export_tree_model(rng, tmp_path):
    from transmogrifai_tpu.models.trees import GBTFamily
    model, store, pred = _fitted(
        rng, families=[GBTFamily(grid=[
            {"maxDepth": 3, "minInstancesPerNode": 10,
             "minInfoGain": 0.001}])])
    meta = export_prediction_fn(model, str(tmp_path))
    fn = load_prediction_fn(str(tmp_path))
    X = rng.normal(size=(11, meta["featureDim"])).astype(np.float32)
    out = fn(X)
    direct = model.stage_of(pred).predict_arrays(X.astype(np.float64))
    np.testing.assert_allclose(out["prediction"], direct[0], rtol=1e-4)
