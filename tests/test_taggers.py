"""Vendored POS/NER/sentence taggers (VERDICT r3 #5): the model-based
taggers must load shipped weights and beat the round-2 capitalization
heuristic on a held-out fixture. Fixture sentences were written by hand
(not drawn from the training generator's output)."""
import numpy as np

from transmogrifai_tpu.columns import ColumnStore, column_from_values
from transmogrifai_tpu.ops.text_suite import (NameEntityRecognizer,
                                              OpPOSTagger,
                                              OpSentenceSplitter,
                                              split_sentences)
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.utils.taggers import load_tagger

# held-out NER fixture: (sentence, gold entity spans)
NER_FIXTURE = [
    ("Yesterday Maria Garcia joined Initech Corp in Berlin .",
     {"Maria Garcia", "Initech Corp", "Berlin"}),
    ("The quarterly report was reviewed by Wayne Industries near Toronto .",
     {"Wayne Industries", "Toronto"}),
    ("Finally David Kim presented the annual budget at Zenith Labs .",
     {"David Kim", "Zenith Labs"}),
    ("Recently , Omar Hassan visited Stark Industries near Madrid .",
     {"Omar Hassan", "Stark Industries", "Madrid"}),
    ("The big team shipped the new release in March .", set()),
    ("Carlos Silva met Helen Brooks at Apex Bank in Chicago .",
     {"Carlos Silva", "Helen Brooks", "Apex Bank", "Chicago"}),
    ("Soon the engineers reviewed each critical issue carefully .", set()),
    ("Laura Chen moved to Seattle with Rachel Kumar .",
     {"Laura Chen", "Seattle", "Rachel Kumar"}),
]


def _span_f1(pred_sets, gold_sets):
    tp = sum(len(p & g) for p, g in zip(pred_sets, gold_sets))
    fp = sum(len(p - g) for p, g in zip(pred_sets, gold_sets))
    fn = sum(len(g - p) for p, g in zip(pred_sets, gold_sets))
    prec = tp / max(tp + fp, 1)
    rec = tp / max(tp + fn, 1)
    return 2 * prec * rec / max(prec + rec, 1e-9)


def test_ner_model_loads_and_beats_heuristic():
    assert load_tagger("ner") is not None, "vendored NER weights missing"
    stage = NameEntityRecognizer()
    gold = [g for _, g in NER_FIXTURE]
    model_pred = [set(stage.tag_sentence(s.split()))
                  for s, _ in NER_FIXTURE]
    heur_pred = [set(stage._heuristic_spans(s.split()))
                 for s, _ in NER_FIXTURE]
    f1_model = _span_f1(model_pred, gold)
    f1_heur = _span_f1(heur_pred, gold)
    assert f1_model > f1_heur, (f1_model, f1_heur, model_pred)
    assert f1_model >= 0.85, (f1_model, model_pred)


def test_ner_entity_type_filter():
    stage = NameEntityRecognizer(entity_types=["PER"])
    spans = stage.tag_sentence(
        "Carlos Silva met Helen Brooks at Apex Bank in Chicago .".split())
    assert "Carlos Silva" in spans and "Helen Brooks" in spans
    assert "Apex Bank" not in spans and "Chicago" not in spans


def test_sentence_splitter_handles_abbreviations():
    assert load_tagger("sent") is not None
    text = ("Dr. Smith met Maria Garcia in Paris. They reviewed the "
            "3.5 budget. Prof. Chen left early!")
    sents = split_sentences(text)
    assert sents == [
        "Dr. Smith met Maria Garcia in Paris.",
        "They reviewed the 3.5 budget.",
        "Prof. Chen left early!",
    ]
    # U.S.-style internal dots stay inside
    assert len(split_sentences(
        "The U.S. office approved the plan. Work starts in March.")) == 2


def test_sentence_splitter_stage_and_pos_stage():
    store = ColumnStore({
        "t": column_from_values(ft.Text, [
            "Anna Lopez signed the contract. The team shipped it.",
            None]),
    })
    from transmogrifai_tpu import FeatureBuilder
    t = FeatureBuilder.Text("t").from_column().as_predictor()
    sent_stage = OpSentenceSplitter().set_input(t)
    col = sent_stage.transform_columns(store)
    assert col.get_raw(0) == ["Anna Lopez signed the contract.",
                              "The team shipped it."]
    assert col.get_raw(1) == []

    pos_stage = OpPOSTagger().set_input(t)
    pcol = pos_stage.transform_columns(store)
    tagged = pcol.get_raw(0)
    assert any(x.endswith("/NNP") for x in tagged[:2]), tagged
    assert any(x.startswith("the/DT") or x.startswith("The/DT")
               for x in tagged), tagged


def test_pos_tagger_basic_accuracy():
    pos = load_tagger("pos")
    assert pos is not None
    toks = "The new engineer reviewed the quarterly report in Boston .".split()
    tags = pos.tag(toks)
    gold = ["DT", "JJ", "NN", "VBD", "DT", "JJ", "NN", "IN", "NNP", "."]
    acc = np.mean([t == g for t, g in zip(tags, gold)])
    assert acc >= 0.8, list(zip(toks, tags))


def test_ner_production_path_with_honorifics():
    """The PRODUCTION tokenization path (split_sentences + _ner_tokenize
    inside transform_columns) must not emit honorific titles as entities
    — a train/inference tokenization mismatch did exactly that."""
    from transmogrifai_tpu import FeatureBuilder

    store = ColumnStore({
        "t": column_from_values(ft.Text, [
            "Dr. Smith met Maria Garcia in Paris.",
            "Mr. Jones visited Wayne Industries near Toronto."]),
    })
    t = FeatureBuilder.Text("t").from_column().as_predictor()
    stage = NameEntityRecognizer().set_input(t)
    out = stage.transform_columns(store)
    for i in range(2):
        ents = out.values[i]
        assert not any(e in {"Dr", "Mr", "Mrs", "Ms", "Prof"}
                       for e in ents), ents
    assert "Maria Garcia" in out.values[0]
    assert "Smith" in out.values[0]
    assert "Wayne Industries" in out.values[1]
