"""Cross-process observability tests (PR 15): distributed tracing,
the live /metrics plane, latency decomposition and the MFU block.

The tentpole contract: one request, one trace — a traceparent minted at
the fleet router survives the HTTP hop into the worker, the worker's
request span rides into the micro-batcher, the batch span LINKS its
member request span ids, and a retrain subprocess joins the triggering
window's trace via TMOG_TRACE_PARENT; every process writes an atomic
trace shard and `trace merge` stitches them into one clock-aligned
Perfetto file. The /metrics plane: every scrape is VALID Prometheus
0.0.4 text (asserted by this module's own independent parser — not the
runtime's), histogram buckets are monotonically cumulative with
+Inf == _count even under concurrent observe() hammering (the
torn-scrape fix), and the router's aggregate equals the sum of its
workers' scrapes. Satellites: the TMG313 metric-name self-lint rule
and the executed-FLOP device-cost (mfu) block."""
import http.client
import json
import math
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from transmogrifai_tpu import (FeatureBuilder, Workflow, serving,
                               telemetry)
from transmogrifai_tpu import server as server_mod
from transmogrifai_tpu.models import (BinaryClassificationModelSelector,
                                      LogisticRegressionFamily)
from transmogrifai_tpu.ops.transmogrifier import transmogrify

BUCKET_CAP = 64


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


# ---------------------------------------------------------------------------
# the test suite's OWN minimal Prometheus 0.0.4 text parser — independent
# of telemetry.parse_prometheus on purpose: the runtime must not grade
# its own homework
# ---------------------------------------------------------------------------


def parse_prom(text: str):
    """{family: {"type": t, "samples": {(name, labels): float}}};
    raises on anything that is not valid exposition text."""
    fams = {}
    assert text.endswith("\n"), "exposition must end with a newline"
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            _h, _t, fam, kind = line.split()
            assert kind in ("counter", "gauge", "histogram", "untyped")
            fams[fam] = {"type": kind, "samples": {}}
            continue
        if line.startswith("#"):
            continue
        if "{" in line:
            name = line[:line.index("{")]
            labels = line[line.index("{"):line.index("}") + 1]
            value = line[line.index("}") + 1:].strip()
        else:
            name, value = line.rsplit(None, 1)
            labels = ""
        v = float(value)          # non-numeric -> ValueError
        fam = name
        for suf in ("_bucket", "_sum", "_count"):
            base = name[:-len(suf)] if name.endswith(suf) else None
            if base and fams.get(base, {}).get("type") == "histogram":
                fam = base
                break
        fams.setdefault(fam, {"type": "untyped", "samples": {}})
        fams[fam]["samples"][(name, labels)] = v
    return fams


def assert_histograms_valid(fams):
    """Every histogram family: per-le counts monotonically cumulative,
    +Inf bucket == _count."""
    for fam, doc in fams.items():
        if doc["type"] != "histogram":
            continue
        buckets = []
        inf = total = None
        for (name, labels), v in doc["samples"].items():
            if name == fam + "_bucket":
                le = labels.split('le="')[1].split('"')[0]
                if le == "+Inf":
                    inf = v
                else:
                    buckets.append((float(le), v))
            elif name == fam + "_count":
                total = v
        buckets.sort()
        prev = 0.0
        for le, v in buckets:
            assert v >= prev, (fam, le, v, prev)
            prev = v
        assert inf is not None and total is not None, fam
        assert inf == total, (fam, inf, total)
        if buckets:
            assert buckets[-1][1] <= inf, (fam, buckets[-1], inf)


# ---------------------------------------------------------------------------
# trace context primitives
# ---------------------------------------------------------------------------


def test_traceparent_roundtrip_and_malformed():
    ctx = telemetry.mint_trace()
    tp = telemetry.format_traceparent(*ctx)
    assert telemetry.parse_traceparent(tp) == ctx
    for bad in (None, "", "zz", "00-short-short-01",
                "00-" + "g" * 32 + "-" + "0" * 16 + "-01"):
        assert telemetry.parse_traceparent(bad) is None
    # ids are well-formed hex of the W3C widths
    assert len(ctx[0]) == 32 and len(ctx[1]) == 16
    int(ctx[0], 16), int(ctx[1], 16)
    # and unique across mints
    assert telemetry.mint_trace()[0] != ctx[0]


def test_span_trace_identity_and_nesting():
    telemetry.enable()
    ctx = telemetry.mint_trace()
    with telemetry.trace_scope(telemetry.format_traceparent(*ctx)):
        with telemetry.span("outer") as outer:
            assert outer.trace_id == ctx[0]
            assert outer.parent_id == ctx[1]
            with telemetry.span("inner") as inner:
                assert inner.trace_id == ctx[0]
                assert inner.parent_id == outer.span_id
    # outside any scope spans stay untraced (no id args recorded)
    with telemetry.span("plain") as sp:
        assert sp.trace_id is None
    evs = {e["name"]: e for e in telemetry.trace_events()
           if e.get("ph") == "X"}
    assert evs["outer"]["args"]["trace_id"] == ctx[0]
    assert evs["inner"]["args"]["parent_span_id"] \
        == evs["outer"]["args"]["span_id"]
    assert "trace_id" not in evs["plain"]["args"]


def test_trace_scope_none_is_noop_and_disabled_span_has_no_ids():
    with telemetry.trace_scope(None):
        assert telemetry.current_trace() is None
    sp = telemetry.span("x")          # disabled -> null span
    assert sp.trace_id is None and sp.span_id is None


def test_trace_shard_write_merge_and_clock_alignment(tmp_path):
    telemetry.enable()
    with telemetry.trace_scope(telemetry.mint_trace()):
        with telemetry.span("a"):
            pass
    d = str(tmp_path / "shards")
    p = telemetry.write_trace_shard(d, role="worker")
    assert p and os.path.exists(p)
    # a second process's shard, hand-crafted with a LATER clock epoch:
    # the merger must shift its events right by the offset
    with open(p) as fh:
        mine = json.load(fh)
    other = {"role": "router", "pid": mine["pid"] + 1,
             "epochUnixS": mine["epochUnixS"] + 2.0,
             "traceEvents": [{"name": "r", "ph": "X", "pid": 0,
                              "tid": 0, "ts": 10.0, "dur": 5.0,
                              "args": {}}]}
    with open(os.path.join(d, "shard-router-9.trace.json"), "w") as fh:
        json.dump(other, fh)
    merged = telemetry.merge_trace_shards(d)
    assert merged["mergedShards"] == 2
    rows = {e["args"]["name"] for e in merged["traceEvents"]
            if e["name"] == "process_name"}
    assert f"worker-{mine['pid']}" in rows
    assert f"router-{mine['pid'] + 1}" in rows
    r_ev = [e for e in merged["traceEvents"] if e["name"] == "r"][0]
    assert math.isclose(r_ev["ts"], 10.0 + 2e6, rel_tol=1e-9)
    assert r_ev["pid"] == mine["pid"] + 1
    # a torn shard is skipped with a note, never fatal
    with open(os.path.join(d, "shard-torn-1.trace.json"), "w") as fh:
        fh.write("{not json")
    merged2 = telemetry.merge_trace_shards(d)
    assert merged2["mergedShards"] == 2
    assert merged2["mergeErrors"]
    # merging INTO the shard directory is idempotent: a re-run must not
    # ingest the previous merge's own output as a shard (it has no
    # epoch anchor and would both duplicate every span and destroy the
    # clock alignment)
    telemetry.write_merged_trace(
        d, os.path.join(d, "merged.trace.json"))
    merged3 = telemetry.merge_trace_shards(d)
    assert merged3["mergedShards"] == 2
    n_spans = sum(1 for e in merged3["traceEvents"]
                  if e.get("ph") == "X")
    assert n_spans == sum(1 for e in merged2["traceEvents"]
                          if e.get("ph") == "X")


def test_shard_write_skips_when_nothing_recorded(tmp_path):
    assert telemetry.write_trace_shard(str(tmp_path)) is None


def test_env_traceparent_joins_subprocess_spans(tmp_path):
    """The retrain-inheritance mechanism: a fresh interpreter launched
    with TMOG_TRACE_PARENT + TMOG_TRACE_ROLE records spans on the
    PARENT's trace id and names its shard row after its role."""
    ctx = telemetry.mint_trace()
    tp = telemetry.format_traceparent(*ctx)
    d = str(tmp_path / "shards")
    env = dict(os.environ)
    env[telemetry.TRACE_ENV] = tp
    env[telemetry.TRACE_ROLE_ENV] = "retrain"
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    code = (
        "from transmogrifai_tpu import telemetry\n"
        "telemetry.enable()\n"
        "with telemetry.span('child:work'):\n"
        "    pass\n"
        f"print(telemetry.write_trace_shard({d!r}))\n")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    merged = telemetry.merge_trace_shards(d)
    ev = [e for e in merged["traceEvents"]
          if e.get("name") == "child:work"][0]
    assert ev["args"]["trace_id"] == ctx[0]
    assert ev["args"]["parent_span_id"] == ctx[1]
    rows = [e["args"]["name"] for e in merged["traceEvents"]
            if e["name"] == "process_name"]
    assert any(r.startswith("retrain-") for r in rows), rows


def test_retrain_job_records_and_inherits_traceparent(tmp_path):
    from transmogrifai_tpu import lifecycle
    from transmogrifai_tpu.continual import RetrainController

    reg = lifecycle.ModelRegistry(str(tmp_path / "reg"))
    c = RetrainController("m", reg, [sys.executable, "-c", "pass"],
                          job_dir=str(tmp_path / "jobs"),
                          trace_dir=str(tmp_path / "shards"))
    ctx = telemetry.mint_trace()
    with telemetry.trace_scope(ctx):
        job = c._new_job({"reason": "test"})
    assert telemetry.parse_traceparent(job["traceparent"]) == ctx
    env = c._spawn_env(job, None)
    assert env[telemetry.TRACE_ENV] == job["traceparent"]
    assert env[telemetry.TRACE_ROLE_ENV] == "retrain"
    assert env["TMOG_TRACE_DIR"] == str(tmp_path / "shards")
    # untraced trigger mints a root rather than riding untraced
    job2 = c._new_job(None)
    assert telemetry.parse_traceparent(job2["traceparent"]) is not None


# ---------------------------------------------------------------------------
# torn-scrape fix: hammer the histogram while scraping
# ---------------------------------------------------------------------------


def test_histogram_scrape_hammer_never_tears():
    telemetry.enable()
    h = telemetry.histogram("hammer.seconds")
    stop = threading.Event()
    rng = np.random.default_rng(7)
    values = rng.exponential(0.05, 4096).tolist()

    def hammer():
        i = 0
        while not stop.is_set():
            h.observe(values[i % len(values)])
            i += 1

    threads = [threading.Thread(target=hammer, name=f"hammer-{i}",
                                daemon=True) for i in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(300):
            fams = parse_prom(telemetry.render_prometheus())
            assert_histograms_valid(fams)
            doc = telemetry.metrics_json()["hammer.seconds"]
            # the JSON snapshot obeys the same invariant
            buckets = sorted((float(k), v)
                             for k, v in doc["buckets"].items())
            prev = 0
            for _le, v in buckets:
                assert v >= prev
                prev = v
            assert buckets[-1][1] <= doc["count"]
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)


def test_histogram_bucket_semantics_exact():
    telemetry.enable()
    h = telemetry.histogram("exact.seconds", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.1, 0.5, 1.0, 5.0, 100.0):
        h.observe(v)
    counts, total, count = h.snapshot()
    # v <= le semantics: 0.1 holds {0.05, 0.1}; 1.0 adds {0.5, 1.0};
    # 10.0 adds {5.0}; 100.0 only reaches +Inf (== count)
    assert counts == (2, 4, 5)
    assert count == 6
    assert abs(total - 106.65) < 1e-9
    assert h.bucket_counts() == {0.1: 2, 1.0: 4, 10.0: 5}


# ---------------------------------------------------------------------------
# exposition aggregation (the router's /metrics plane)
# ---------------------------------------------------------------------------


def test_render_prometheus_sum_equals_per_worker_sums():
    telemetry.enable()
    telemetry.counter("w.requests").inc(3)
    telemetry.histogram("w.lat", buckets=(0.1, 1.0)).observe(0.05)
    text1 = telemetry.render_prometheus()
    telemetry.counter("w.requests").inc(2)
    telemetry.histogram("w.lat", buckets=(0.1, 1.0)).observe(0.5)
    text2 = telemetry.render_prometheus()
    merged = telemetry.render_prometheus_sum([text1, text2])
    fams = parse_prom(merged)
    assert_histograms_valid(fams)
    f1, f2 = parse_prom(text1), parse_prom(text2)
    for fam, doc in fams.items():
        for key, v in doc["samples"].items():
            expect = (f1.get(fam, {}).get("samples", {}).get(key, 0)
                      + f2.get(fam, {}).get("samples", {}).get(key, 0))
            assert v == expect, (fam, key, v, expect)


def test_parse_prometheus_rejects_garbage():
    with pytest.raises(ValueError):
        telemetry.parse_prometheus("what even is this line\n")
    with pytest.raises(ValueError):
        telemetry.parse_prometheus("x{le=\"0.1\"} notanumber\n")


# ---------------------------------------------------------------------------
# MFU / device-cost block
# ---------------------------------------------------------------------------


def test_device_cost_ledger_and_block_shape():
    telemetry.reset_device_cost()
    telemetry.record_device_work("scoring", flops=2e9, seconds=0.01)
    telemetry.record_device_work("scoring", flops=2e9, seconds=0.01)
    telemetry.record_device_work("tuning", flops=5e9)   # untimed
    st = telemetry.device_cost_stats()
    assert st["phases"]["scoring"]["dispatches"] == 2
    assert st["phases"]["scoring"]["flops"] == 4e9
    assert st["phases"]["tuning"]["achieved_tflops"] is None
    assert st["device_flops"] == 9e9
    # the rate pairs TIMED flops with timed seconds only: 4e9 / 0.02
    assert abs(st["achieved_tflops"] - 0.2) < 1e-6
    for k in ("device_kind", "devices", "mfu_bf16_pct", "mfu_f32_pct"):
        assert k in st
    telemetry.reset_device_cost()


def test_scoring_engine_feeds_device_cost(rng):
    telemetry.reset_device_cost()
    n = 256
    y = rng.integers(0, 2, n).astype(float)
    x = rng.normal(size=n) + y
    records = [{"label": float(y[i]), "x": float(x[i])}
               for i in range(n)]
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    f1 = FeatureBuilder.Real("x").from_column().as_predictor()
    vec = transmogrify([f1])
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()],
        splitter=None, seed=5)
    pred = label.transform_with(sel, vec)
    model = (Workflow().set_input_records(records)
             .set_result_features(pred).train())
    eng = model.scoring_engine(gate_bandwidth=False, mesh=False)
    assert eng is not None
    eng.score_store(records, use_cache=False)   # compile dispatch
    before = telemetry.device_cost_stats()["phases"].get(
        "scoring", {"dispatches": 0})["dispatches"]
    eng.score_store(records, use_cache=False)   # warm dispatch
    st = telemetry.device_cost_stats()["phases"]["scoring"]
    assert st["dispatches"] > before
    assert st["flops"] > 0 and st["seconds"] > 0


def test_runner_metrics_doc_stamps_mfu(rng, tmp_path):
    from transmogrifai_tpu.runner import (OpParams, OpWorkflowRunner,
                                          RunType)
    n = 120
    y = rng.integers(0, 2, n).astype(float)
    x = rng.normal(size=n) + y
    records = [{"label": float(y[i]), "x": float(x[i])}
               for i in range(n)]
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    f1 = FeatureBuilder.Real("x").from_column().as_predictor()
    vec = transmogrify([f1])
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()],
        splitter=None, seed=6)
    pred = label.transform_with(sel, vec)
    wf = Workflow().set_input_records(records).set_result_features(pred)
    params = OpParams(model_location=str(tmp_path / "model"))
    res = OpWorkflowRunner(wf).run(RunType.TRAIN, params)
    assert "mfu" in res.metrics
    blk = res.metrics["mfu"]
    assert "phases" in blk and "device_flops" in blk
    assert blk["device_flops"] > 0          # the CV sweep dispatched


def test_runner_trace_dir_writes_shard(rng, tmp_path):
    from transmogrifai_tpu.runner import (OpParams, OpWorkflowRunner,
                                          RunType)
    n = 120
    y = rng.integers(0, 2, n).astype(float)
    x = rng.normal(size=n) + y
    records = [{"label": float(y[i]), "x": float(x[i])}
               for i in range(n)]
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    f1 = FeatureBuilder.Real("x").from_column().as_predictor()
    vec = transmogrify([f1])
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()],
        splitter=None, seed=7)
    pred = label.transform_with(sel, vec)
    wf = Workflow().set_input_records(records).set_result_features(pred)
    d = str(tmp_path / "shards")
    params = OpParams(model_location=str(tmp_path / "model"),
                      custom_params={"traceDir": d, "validate": False,
                                     "plan": False})
    OpWorkflowRunner(wf).run(RunType.TRAIN, params)
    shards = [f for f in os.listdir(d) if f.endswith(".trace.json")]
    assert len(shards) == 1 and "run-train" in shards[0]
    # run-scoped: recording turned back off afterwards
    assert not telemetry.enabled()


# ---------------------------------------------------------------------------
# worker surface: /metrics, decomposition, batch span links
# ---------------------------------------------------------------------------


def _train_tiny(seed, n=160):
    rng = np.random.default_rng(seed)
    y = np.asarray([i % 2 for i in range(n)], float)
    rng.shuffle(y)
    records = [{"label": float(y[i]),
                "x1": float(rng.normal() + y[i]),
                "x2": float(rng.normal())} for i in range(n)]
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    f1 = FeatureBuilder.Real("x1").from_column().as_predictor()
    f2 = FeatureBuilder.Real("x2").from_column().as_predictor()
    vec = transmogrify([f1, f2])
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()],
        splitter=None, seed=seed)
    pred = label.transform_with(sel, vec)
    model = (Workflow().set_input_records(records)
             .set_result_features(pred).train())
    return model, records


@pytest.fixture(scope="module")
def tiny_server():
    model, records = _train_tiny(31)
    srv = server_mod.ModelServer(batch_deadline_s=0.001)
    srv.register("m", model=model)
    httpd = server_mod.serve_http(srv, port=0)
    yield srv, httpd.server_address[1], records
    httpd.shutdown()
    srv.shutdown(drain=True)
    model._engine_breaker().reset()


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        return r.status, r.getheader("Content-Type"), r.read()
    finally:
        conn.close()


def _post_score(port, name, records, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    try:
        hdrs = {"Content-Type": "application/json"}
        hdrs.update(headers or {})
        conn.request("POST", f"/v1/models/{name}:score",
                     json.dumps({"records": records}), hdrs)
        r = conn.getresponse()
        return (r.status, dict(r.getheaders()),
                json.loads(r.read() or b"{}"))
    finally:
        conn.close()


def test_worker_metrics_endpoint_scrapes_valid(tiny_server):
    srv, port, records = tiny_server
    # valid even with telemetry OFF (always-on server_* gauges ride)
    status, ctype, body = _get(port, "/metrics")
    assert status == 200 and "text/plain" in ctype
    fams = parse_prom(body.decode())
    assert "server_tally_requests" in fams
    telemetry.enable()
    _post_score(port, "m", records[:3])
    status, _c, body = _get(port, "/metrics")
    fams = parse_prom(body.decode())
    assert_histograms_valid(fams)
    assert any(f.startswith("server_queue_wait_seconds") for f in fams)
    assert any(f.startswith("server_device_dispatch_seconds")
               for f in fams)


def test_request_trace_header_adopted_echoed_and_linked(tiny_server):
    srv, port, records = tiny_server
    telemetry.enable()
    ctx = telemetry.mint_trace()
    tp = telemetry.format_traceparent(*ctx)
    status, headers, doc = _post_score(port, "m", records[:2],
                                       {telemetry.TRACE_HEADER: tp})
    assert status == 200, doc
    assert headers.get(telemetry.TRACE_HEADER) == tp
    evs = [e for e in telemetry.trace_events() if e.get("ph") == "X"]
    req = [e for e in evs if e["name"] == "server:request"
           and e["args"].get("trace_id") == ctx[0]]
    assert req, "request span must adopt the header's trace id"
    disp = [e for e in evs if e["name"] == "server:dispatch"
            and e["args"].get("trace_id") == ctx[0]]
    assert disp, "batch span must share the trace id"
    assert req[0]["args"]["span_id"] in disp[0]["args"]["links"]


def test_latency_decomposition_in_stats(tiny_server):
    srv, port, records = tiny_server
    for _ in range(3):
        srv.score("m", records[:4], timeout_s=120)
    st = srv.stats()["models"]["m"]
    lat = st["latency"]
    for ph in ("e2e", "queueWait", "coalesceHold", "deviceDispatch",
               "scatter"):
        assert ph in lat
        assert lat[ph], f"{ph} reservoir must have recorded"
        assert set(lat[ph]) == {"p50_ms", "p95_ms", "p99_ms"}
    # phases are bounded by the end-to-end number they decompose
    assert lat["queueWait"]["p50_ms"] <= lat["e2e"]["p99_ms"]
    assert lat["deviceDispatch"]["p50_ms"] <= lat["e2e"]["p99_ms"]


# ---------------------------------------------------------------------------
# TMG313 self-lint fixtures
# ---------------------------------------------------------------------------


def _load_tmoglint():
    import importlib.util
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "tmoglint", os.path.join(repo, "tools", "tmoglint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_tmg313_dynamic_metric_name_flagged_and_allowlisted():
    tm = _load_tmoglint()
    bad = ("from transmogrifai_tpu import telemetry\n"
           "telemetry.counter(f'x.{k}').inc()\n")
    assert [f.rule for f in tm.lint_source(bad, "pkg/mod.py")] \
        == ["TMG313"]
    from_import = ("from transmogrifai_tpu.telemetry import histogram\n"
                   "histogram(name_var).observe(1)\n")
    assert [f.rule for f in tm.lint_source(from_import, "pkg/mod.py")] \
        == ["TMG313"]
    clean = ("from transmogrifai_tpu import telemetry\n"
             "telemetry.gauge('x.depth').set(1)\n")
    assert tm.lint_source(clean, "pkg/mod.py") == []
    marked = ("from transmogrifai_tpu import telemetry\n"
              "telemetry.counter(f'x.{k}').inc()"
              "  # lint: metric-name — fixed tally catalog\n")
    assert tm.lint_source(marked, "pkg/mod.py") == []
    home = ("import telemetry\n"
            "telemetry.counter(n).inc()\n")
    assert tm.lint_source(home, "transmogrifai_tpu/telemetry.py") == []
    tests_ok = ("from transmogrifai_tpu import telemetry\n"
                "telemetry.counter(nm).inc()\n")
    assert tm.lint_source(tests_ok, "tests/test_x.py") == []


def test_tmg313_in_rules_catalog():
    from transmogrifai_tpu import lint
    assert lint.RULES["TMG313"][0] == "error"


# ---------------------------------------------------------------------------
# CLI: gen/check knobs + trace merge
# ---------------------------------------------------------------------------


def test_cli_trace_merge(tmp_path, capsys):
    from transmogrifai_tpu.cli import run_trace
    telemetry.enable()
    with telemetry.trace_scope(telemetry.mint_trace()):
        with telemetry.span("cli:span"):
            pass
    d = str(tmp_path / "shards")
    telemetry.write_trace_shard(d, role="worker")
    out_path = str(tmp_path / "merged.json")
    assert run_trace("merge", d, out=out_path) == 0
    with open(out_path) as fh:
        doc = json.load(fh)
    assert doc["mergedShards"] == 1
    assert any(e.get("name") == "cli:span" for e in doc["traceEvents"])
    assert run_trace("merge", str(tmp_path / "empty")) == 1
    assert run_trace("resolve", d) == 1


def test_cli_gen_emits_and_check_validates_observability_knobs(tmp_path):
    from transmogrifai_tpu.cli import generate_project, run_check
    csv = tmp_path / "d.csv"
    csv.write_text("label,x\n1,0.5\n0,0.2\n1,0.9\n0,0.1\n")
    out = generate_project(str(csv), "label", str(tmp_path / "proj"))
    params = json.loads(open(out["params.json"]).read())
    assert params["customParams"]["serveMetrics"] is None
    assert params["customParams"]["traceDir"] is None
    bad = dict(params)
    bad["customParams"] = dict(params["customParams"],
                               serveMetrics="nope", traceDir=7)
    bad_path = tmp_path / "bad.json"
    bad_path.write_text(json.dumps(bad))
    assert run_check(str(bad_path)) == 1


# ---------------------------------------------------------------------------
# fleet acceptance: one request, one trace, across real processes
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def traced_fleet(tmp_path_factory):
    from transmogrifai_tpu import resilience
    from transmogrifai_tpu.fleet import FleetSupervisor, serve_fleet_http
    from transmogrifai_tpu.lifecycle import ModelRegistry

    reg_dir = str(tmp_path_factory.mktemp("registry"))
    reg = ModelRegistry(reg_dir)
    model, records = _train_tiny(41)
    mdir = str(tmp_path_factory.mktemp("model"))
    edir = str(tmp_path_factory.mktemp("export"))
    model.save(mdir, overwrite=True)
    serving.export_scoring_fn(model, edir, records[:8],
                              bucket_cap=BUCKET_CAP)
    reg.register("churn", mdir, bank_dir=edir, promote=True)
    trace_dir = str(tmp_path_factory.mktemp("traces"))
    params = tmp_path_factory.mktemp("params") / "params.json"
    params.write_text(json.dumps({"customParams": {
        "registryDir": reg_dir, "serveBucketCap": BUCKET_CAP,
        "serveBatchDeadlineMs": 1.0, "traceDir": trace_dir}}))
    backoff = resilience.RetryPolicy(max_attempts=8, base_delay_s=0.05,
                                     max_delay_s=0.5, jitter=0.1, seed=3)
    sup = FleetSupervisor(str(params), workers=2, respawn_max=6,
                          probe_interval_s=0.1, backoff=backoff)
    sup.start()
    sup.wait_ready(timeout_s=240)
    httpd = serve_fleet_http(sup, port=0, retry_budget=2,
                             forward_timeout_s=120.0)
    yield sup, httpd, httpd.server_address[1], records, trace_dir
    httpd.shutdown()
    sup.stop(drain=True)
    model._engine_breaker().reset()


def test_fleet_router_metrics_aggregates_worker_scrapes(traced_fleet):
    # runs BEFORE the acceptance test below, which drains the fleet
    sup, httpd, port, records, trace_dir = traced_fleet
    # traffic so the workers have non-zero tallies
    for i in range(3):
        status, _h, _doc = _post_score(port, "churn",
                                       records[i:i + 2])
        assert status == 200
    status, ctype, body = _get(port, "/metrics")
    assert status == 200 and "text/plain" in ctype
    fams = parse_prom(body.decode())
    assert_histograms_valid(fams)
    assert fams["fleet_metrics_workers"]["samples"][
        ("fleet_metrics_workers", "")] == 2.0
    # router sums equal the per-worker sums, fetched directly
    worker_totals = 0.0
    for h in sup.ready_workers():
        st, _c, wbody = _get(h.port, "/metrics")
        assert st == 200
        wfams = parse_prom(wbody.decode())
        assert_histograms_valid(wfams)
        worker_totals += wfams["server_tally_requests"]["samples"][
            ("server_tally_requests", "")]
    agg = fams["server_tally_requests"]["samples"][
        ("server_tally_requests", "")]
    assert agg == worker_totals
    assert worker_totals >= 3


def test_fleet_trace_acceptance_one_request_one_trace(traced_fleet):
    """The PR's acceptance bar: one scored request through a live
    2-worker fleet with tracing on yields, after trace merge, a single
    Perfetto file where the router's route span, the worker's request
    span and the micro-batcher's dispatch span share ONE trace id, with
    the batch span linking the request's span id — across real
    processes."""
    sup, httpd, port, records, trace_dir = traced_fleet
    # warm the serving path first so the traced request is steady-state
    status, _h, _doc = _post_score(port, "churn", records[:2])
    assert status == 200
    telemetry.enable()
    telemetry.set_trace_role("router")
    ctx = telemetry.mint_trace()
    tp = telemetry.format_traceparent(*ctx)
    status, _h, doc = _post_score(port, "churn", records[:3],
                                  {telemetry.TRACE_HEADER: tp})
    assert status == 200, doc
    assert doc["rows"] == 3
    # drain the fleet: each worker's serve process writes its shard on
    # SIGTERM (cli.run_serve), the router (this process) writes its own
    sup.stop(drain=True)
    telemetry.write_trace_shard(trace_dir)
    telemetry.set_trace_role("proc")
    merged = telemetry.write_merged_trace(
        trace_dir, os.path.join(trace_dir, "merged.trace.json"))
    assert merged["mergedShards"] >= 2, "router + >=1 worker shard"
    spans = [e for e in merged["traceEvents"] if e.get("ph") == "X"
             and isinstance(e.get("args"), dict)
             and e["args"].get("trace_id") == ctx[0]]
    by_name = {}
    for e in spans:
        by_name.setdefault(e["name"], []).append(e)
    assert "fleet:route" in by_name, sorted(by_name)
    assert "server:request" in by_name, sorted(by_name)
    assert "server:dispatch" in by_name, sorted(by_name)
    route = by_name["fleet:route"][0]
    req = by_name["server:request"][0]
    disp = by_name["server:dispatch"][0]
    # the route span ran in THIS process, the request/dispatch spans in
    # a worker process — one trace, multiple pids
    assert route["pid"] != req["pid"]
    assert req["pid"] == disp["pid"]
    assert req["args"]["span_id"] in disp["args"]["links"]
    # every span of the trace agrees on the id the router minted
    assert {e["args"]["trace_id"] for e in spans} == {ctx[0]}
