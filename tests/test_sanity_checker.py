"""SanityChecker tests (SanityCheckerTest analog)."""
import numpy as np
import pytest

from transmogrifai_tpu import ColumnStore, FeatureBuilder, column_from_values
from transmogrifai_tpu.columns import VectorColumn
from transmogrifai_tpu.ops.sanity_checker import SanityChecker
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.vector_metadata import (VectorColumnMetadata,
                                               VectorMetadata, NULL_INDICATOR)


def _store_with_meta(rng, n=200):
    y = rng.integers(0, 2, size=n).astype(float)
    x_good = rng.normal(size=n) + 0.5 * y
    x_const = np.full(n, 3.0)            # zero variance
    x_leak = y * 2.0 - 1.0               # perfect correlation with label
    x_noise = rng.normal(size=n)
    X = np.stack([x_good, x_const, x_leak, x_noise], axis=1)
    meta = VectorMetadata("features", [
        VectorColumnMetadata("good", "Real"),
        VectorColumnMetadata("const", "Real"),
        VectorColumnMetadata("leak", "Real"),
        VectorColumnMetadata("noise", "Real"),
    ])
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "features": VectorColumn(ft.OPVector, X, meta),
    })
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = FeatureBuilder.OPVector("features").from_column().as_predictor()
    return store, label, feats


def test_drops_zero_variance_and_leaky(rng):
    store, label, feats = _store_with_meta(rng)
    checker = SanityChecker(remove_bad_features=True,
                            remove_feature_group=False)
    label.transform_with(checker, feats)
    model = checker.fit(store)
    kept_names = [model.summary_.names[i] for i in model.keep_indices]
    assert "good_0" in kept_names and "noise_3" in kept_names
    assert "const_1" not in kept_names  # zero variance
    assert "leak_2" not in kept_names   # |corr| > 0.95
    dropped = {d["name"]: d["reasons"] for d in model.summary_.dropped}
    assert any("variance" in r for r in dropped["const_1"])
    assert any("corr" in r for r in dropped["leak_2"])
    out = model.transform_columns(store)
    assert out.values.shape[1] == len(model.keep_indices)
    assert out.metadata.size == len(model.keep_indices)


def test_keeps_all_when_removal_off(rng):
    store, label, feats = _store_with_meta(rng)
    checker = SanityChecker(remove_bad_features=False)
    label.transform_with(checker, feats)
    model = checker.fit(store)
    assert len(model.keep_indices) == 4
    assert len(model.summary_.dropped) > 0  # still reported


def test_cramers_v_flags_leaky_categorical(rng):
    n = 300
    y = rng.integers(0, 2, size=n).astype(float)
    # categorical perfectly aligned with label, one-hot into 2 slots
    cat = np.stack([y, 1 - y], axis=1)
    noise = rng.normal(size=(n, 1))
    X = np.concatenate([cat, noise], axis=1)
    meta = VectorMetadata("features", [
        VectorColumnMetadata("cat", "PickList", grouping="cat",
                             indicator_value="a"),
        VectorColumnMetadata("cat", "PickList", grouping="cat",
                             indicator_value="b"),
        VectorColumnMetadata("noise", "Real"),
    ])
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "features": VectorColumn(ft.OPVector, X, meta),
    })
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = FeatureBuilder.OPVector("features").from_column().as_predictor()
    checker = SanityChecker(remove_bad_features=True)
    label.transform_with(checker, feats)
    model = checker.fit(store)
    kept = [model.summary_.names[i] for i in model.keep_indices]
    assert kept == ["noise_2"]
    stats = model.summary_.categorical_stats
    assert stats and stats[0]["cramersV"] > 0.95


def test_summary_json(rng):
    store, label, feats = _store_with_meta(rng)
    checker = SanityChecker()
    label.transform_with(checker, feats)
    model = checker.fit(store)
    js = model.summary()
    assert "columnStats" in js and len(js["columnStats"]) == 4
    assert "correlationsWithLabel" in js


def test_spearman_and_mutual_info(rng):
    """Spearman rank correlation catches monotone-nonlinear label links that
    Pearson underestimates; contingency stats expose PMI / mutual info
    (SanityChecker.scala:634-638, OpStatistics.scala:300)."""
    from transmogrifai_tpu.columns import VectorColumn
    from transmogrifai_tpu.vector_metadata import (VectorColumnMetadata,
                                                   VectorMetadata)
    n = 400
    y = rng.random(n)
    x_mono = np.exp(6 * y)          # monotone in y, very non-linear
    x_noise = rng.normal(size=n)
    X = np.stack([x_mono, x_noise], axis=1)
    meta = VectorMetadata("features", [
        VectorColumnMetadata("mono", "Real"),
        VectorColumnMetadata("noise", "Real")])
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "features": VectorColumn(ft.OPVector, X, meta)})
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = FeatureBuilder.OPVector("features").from_column().as_predictor()

    checker = SanityChecker(remove_bad_features=False,
                            correlation_type="spearman")
    checker.set_input(label, feats)
    model = checker.fit(store)
    stats = {s["name"]: s for s in model.summary_.column_stats}
    assert stats["mono_0"]["spearmanCorrWithLabel"] == pytest.approx(1.0)
    assert abs(stats["mono_0"]["corrWithLabel"]) < 0.95   # Pearson misses it
    assert abs(stats["noise_1"]["spearmanCorrWithLabel"]) < 0.2

    # pearson-gated checker skips the rank pass (reference computes only
    # the configured CorrelationType)
    cp = SanityChecker(remove_bad_features=False)
    cp.set_input(label, feats)
    mp = cp.fit(store)
    assert mp.summary_.column_stats[0]["spearmanCorrWithLabel"] is None

    # spearman-driven gate removes the monotone leaker
    checker2 = SanityChecker(remove_bad_features=True,
                             correlation_type="spearman",
                             max_correlation=0.95,
                             remove_feature_group=False)
    checker2.set_input(label, feats)
    m2 = checker2.fit(store)
    dropped = {di["name"] for di in m2.summary_.dropped}
    assert any(d.startswith("mono") for d in dropped)


def test_pmi_reported_for_categorical_groups(rng):
    from transmogrifai_tpu.columns import VectorColumn
    from transmogrifai_tpu.vector_metadata import (VectorColumnMetadata,
                                                   VectorMetadata)
    n = 300
    y = rng.integers(0, 2, n).astype(float)
    cat = np.where(y == 1, 0, 1)    # perfectly dependent 2-cat pivot
    X = np.stack([cat == 0, cat == 1], axis=1).astype(float)
    meta = VectorMetadata("features", [
        VectorColumnMetadata("c", "PickList", grouping="c",
                             indicator_value="a"),
        VectorColumnMetadata("c", "PickList", grouping="c",
                             indicator_value="b")])
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "features": VectorColumn(ft.OPVector, X, meta)})
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = FeatureBuilder.OPVector("features").from_column().as_predictor()
    checker = SanityChecker(remove_bad_features=False)
    checker.set_input(label, feats)
    model = checker.fit(store)
    cs = model.summary_.categorical_stats[0]
    assert cs["mutualInfo"] > 0.9           # ~1 bit for perfect dependence
    assert len(cs["pointwiseMutualInfo"]) == 2
