"""SanityChecker tests (SanityCheckerTest analog)."""
import numpy as np
import pytest

from transmogrifai_tpu import ColumnStore, FeatureBuilder, column_from_values
from transmogrifai_tpu.columns import VectorColumn
from transmogrifai_tpu.ops.sanity_checker import SanityChecker
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.vector_metadata import (VectorColumnMetadata,
                                               VectorMetadata, NULL_INDICATOR)


def _store_with_meta(rng, n=200):
    y = rng.integers(0, 2, size=n).astype(float)
    x_good = rng.normal(size=n) + 0.5 * y
    x_const = np.full(n, 3.0)            # zero variance
    x_leak = y * 2.0 - 1.0               # perfect correlation with label
    x_noise = rng.normal(size=n)
    X = np.stack([x_good, x_const, x_leak, x_noise], axis=1)
    meta = VectorMetadata("features", [
        VectorColumnMetadata("good", "Real"),
        VectorColumnMetadata("const", "Real"),
        VectorColumnMetadata("leak", "Real"),
        VectorColumnMetadata("noise", "Real"),
    ])
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "features": VectorColumn(ft.OPVector, X, meta),
    })
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = FeatureBuilder.OPVector("features").from_column().as_predictor()
    return store, label, feats


def test_drops_zero_variance_and_leaky(rng):
    store, label, feats = _store_with_meta(rng)
    checker = SanityChecker(remove_bad_features=True,
                            remove_feature_group=False)
    label.transform_with(checker, feats)
    model = checker.fit(store)
    kept_names = [model.summary_.names[i] for i in model.keep_indices]
    assert "good_0" in kept_names and "noise_3" in kept_names
    assert "const_1" not in kept_names  # zero variance
    assert "leak_2" not in kept_names   # |corr| > 0.95
    dropped = {d["name"]: d["reasons"] for d in model.summary_.dropped}
    assert any("variance" in r for r in dropped["const_1"])
    assert any("corr" in r for r in dropped["leak_2"])
    out = model.transform_columns(store)
    assert out.values.shape[1] == len(model.keep_indices)
    assert out.metadata.size == len(model.keep_indices)


def test_keeps_all_when_removal_off(rng):
    store, label, feats = _store_with_meta(rng)
    checker = SanityChecker(remove_bad_features=False)
    label.transform_with(checker, feats)
    model = checker.fit(store)
    assert len(model.keep_indices) == 4
    assert len(model.summary_.dropped) > 0  # still reported


def test_cramers_v_flags_leaky_categorical(rng):
    n = 300
    y = rng.integers(0, 2, size=n).astype(float)
    # categorical perfectly aligned with label, one-hot into 2 slots
    cat = np.stack([y, 1 - y], axis=1)
    noise = rng.normal(size=(n, 1))
    X = np.concatenate([cat, noise], axis=1)
    meta = VectorMetadata("features", [
        VectorColumnMetadata("cat", "PickList", grouping="cat",
                             indicator_value="a"),
        VectorColumnMetadata("cat", "PickList", grouping="cat",
                             indicator_value="b"),
        VectorColumnMetadata("noise", "Real"),
    ])
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        "features": VectorColumn(ft.OPVector, X, meta),
    })
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = FeatureBuilder.OPVector("features").from_column().as_predictor()
    checker = SanityChecker(remove_bad_features=True)
    label.transform_with(checker, feats)
    model = checker.fit(store)
    kept = [model.summary_.names[i] for i in model.keep_indices]
    assert kept == ["noise_2"]
    stats = model.summary_.categorical_stats
    assert stats and stats[0]["cramersV"] > 0.95


def test_summary_json(rng):
    store, label, feats = _store_with_meta(rng)
    checker = SanityChecker()
    label.transform_with(checker, feats)
    model = checker.fit(store)
    js = model.summary()
    assert "columnStats" in js and len(js["columnStats"]) == 4
    assert "correlationsWithLabel" in js
