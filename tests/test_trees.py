"""Tree ensembles: histogram engine, families, stages, selector wiring.

Mirrors the reference's tree model tests (OpRandomForestClassifierTest,
OpGBTClassifierTest, OpXGBoostClassifierTest) at the contract level:
fit → sensible predictions, grid batching, serialization round-trip.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from transmogrifai_tpu.models import _treefit as TF
from transmogrifai_tpu.models.trees import (
    DecisionTreeFamily, GBTFamily, OpDecisionTreeClassifier,
    OpGBTRegressor, OpRandomForestClassifier, RandomForestFamily,
    TreeEnsembleModel, XGBoostFamily)


@pytest.fixture(scope="module")
def xy_cls():
    rng = np.random.default_rng(0)
    n, d = 300, 6
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = ((X[:, 0] > 0.2) ^ (X[:, 2] < -0.1)).astype(np.float32)
    return X, y


@pytest.fixture(scope="module")
def xy_reg():
    rng = np.random.default_rng(1)
    n, d = 300, 6
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (2.0 * X[:, 0] - X[:, 1] + 0.05 * rng.normal(size=n)).astype(
        np.float32)
    return X, y


def test_binning_roundtrip():
    rng = np.random.default_rng(2)
    X = jnp.asarray(rng.normal(size=(100, 3)).astype(np.float32))
    edges = TF.quantile_bin_edges(X, 8)
    assert edges.shape == (3, 7)
    Xb = TF.binarize(X, edges)
    assert Xb.shape == (100, 3)
    assert int(Xb.min()) >= 0 and int(Xb.max()) <= 7
    # split semantics: bin <= t  ⟺  x <= edges[f, t]
    t = 3
    lhs = np.asarray(Xb[:, 0] <= t)
    rhs = np.asarray(X[:, 0] <= edges[0, t])
    np.testing.assert_array_equal(lhs, rhs)


def test_single_tree_learns_split(xy_cls):
    X, y = xy_cls
    fam = DecisionTreeFamily(
        grid=[{"maxDepth": 4, "minInstancesPerNode": 5,
               "minInfoGain": 0.001}])
    params = jax.jit(
        lambda X, y, w: fam.fit_batch(X, y, w, fam.stack_grid()))(
        jnp.asarray(X), jnp.asarray(y), jnp.ones(len(y), jnp.float32))
    pred, raw, prob = fam.predict_batch(params, jnp.asarray(X))
    acc = float((np.asarray(pred)[0] == y).mean())
    assert acc > 0.9
    # probabilities normalized
    np.testing.assert_allclose(np.asarray(prob)[0].sum(-1), 1.0, atol=1e-4)


def test_depth_grouped_grid(xy_cls):
    """Grid with mixed maxDepth → depth groups padded + reassembled in
    grid order."""
    X, y = xy_cls
    fam = RandomForestFamily(
        grid=[{"maxDepth": 2, "minInstancesPerNode": 5, "minInfoGain": 1e-3},
              {"maxDepth": 4, "minInstancesPerNode": 5, "minInfoGain": 1e-3},
              {"maxDepth": 2, "minInstancesPerNode": 50, "minInfoGain": 1e-3}],
        num_trees=5)
    params = jax.jit(
        lambda X, y, w: fam.fit_batch(X, y, w, fam.stack_grid()))(
        jnp.asarray(X), jnp.asarray(y), jnp.ones(len(y), jnp.float32))
    # global depth 4: feat length 2^4-1, leaf 2^4
    assert params["feat"].shape == (3, 5, 15)
    assert params["leaf"].shape == (3, 5, 16, 2)
    pred, _, prob = fam.predict_batch(params, jnp.asarray(X))
    accs = [float((np.asarray(pred)[g] == y).mean()) for g in range(3)]
    # deeper trees fit better than depth-2 with min 50 instances per node
    assert accs[1] >= accs[2] - 0.02


def test_fold_vmap_grid(xy_cls):
    """fit_batch under an outer fold-vmap (the CV engine's usage)."""
    X, y = xy_cls
    fam = GBTFamily(grid=[{"maxDepth": 3, "minInstancesPerNode": 5,
                           "minInfoGain": 1e-3}], max_iter=5)
    w_folds = jnp.asarray(
        np.stack([np.arange(len(y)) % 3 != k for k in range(3)]
                 ).astype(np.float32))
    stacked = fam.stack_grid()
    params = jax.jit(lambda w: jax.vmap(
        lambda wk: fam.fit_batch(jnp.asarray(X), jnp.asarray(y), wk,
                                 stacked))(w))(w_folds)
    assert params["feat"].shape[:2] == (3, 1)
    pred, _, _ = jax.vmap(lambda p: fam.predict_batch(p, jnp.asarray(X)))(
        params)
    assert np.asarray(pred).shape == (3, 1, len(y))


def test_gbt_improves_with_rounds(xy_reg):
    X, y = xy_reg
    r2 = {}
    for rounds in (2, 20):
        fam = GBTFamily(task="regression",
                        grid=[{"maxDepth": 3, "minInstancesPerNode": 5,
                               "minInfoGain": 0.0}], max_iter=rounds)
        params = jax.jit(
            lambda X, y, w: fam.fit_batch(X, y, w, fam.stack_grid()))(
            jnp.asarray(X), jnp.asarray(y), jnp.ones(len(y), jnp.float32))
        pred, _, _ = fam.predict_batch(params, jnp.asarray(X))
        resid = y - np.asarray(pred)[0]
        r2[rounds] = 1.0 - resid.var() / y.var()
    assert r2[20] > r2[2] + 0.1
    assert r2[20] > 0.7


def test_xgb_binary(xy_cls):
    X, y = xy_cls
    fam = XGBoostFamily(grid=[{"maxDepth": 3, "eta": 0.3,
                               "minChildWeight": 1.0, "numRound": 10}])
    params = jax.jit(
        lambda X, y, w: fam.fit_batch(X, y, w, fam.stack_grid()))(
        jnp.asarray(X), jnp.asarray(y), jnp.ones(len(y), jnp.float32))
    pred, raw, prob = fam.predict_batch(params, jnp.asarray(X))
    assert float((np.asarray(pred)[0] == y).mean()) > 0.9
    p = np.asarray(prob)[0]
    np.testing.assert_allclose(p.sum(-1), 1.0, atol=1e-5)
    # raw margins symmetric
    r = np.asarray(raw)[0]
    np.testing.assert_allclose(r[:, 0], -r[:, 1], atol=1e-5)


def test_stage_fit_and_roundtrip(xy_cls, tmp_path):
    from transmogrifai_tpu.features import FeatureBuilder
    from transmogrifai_tpu.columns import ColumnStore, VectorColumn, \
        column_from_values
    from transmogrifai_tpu.types import feature_types as ft

    X, y = xy_cls
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = FeatureBuilder.OPVector("feats").from_column().as_predictor()
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y.astype(np.float64)),
        "feats": VectorColumn(ft.OPVector, X.astype(np.float64))})
    est = OpRandomForestClassifier(num_trees=5, max_depth=4,
                                   min_instances_per_node=5).set_input(
        label, feats)
    model = est.fit(store)
    pred1, _, prob1 = model.predict_arrays(X.astype(np.float64))
    assert float((pred1 == y).mean()) > 0.8

    # state round-trip
    state = model.get_model_state()
    m2 = TreeEnsembleModel(kind=model.kind, n_classes=model.n_classes,
                           max_depth=model.max_depth)
    m2.apply_model_state(state)
    pred2, _, prob2 = m2.predict_arrays(X.astype(np.float64))
    np.testing.assert_allclose(prob1, prob2, atol=1e-7)

    # row-level transform matches batch transform (OpTransformerSpec idea)
    row = {model.input_features[1].name: X[0].astype(np.float64)}
    out = model.transform_row(row)
    assert abs(out["prediction"] - pred1[0]) < 1e-9


def test_regressor_stage(xy_reg):
    from transmogrifai_tpu.features import FeatureBuilder
    from transmogrifai_tpu.columns import ColumnStore, VectorColumn, \
        column_from_values
    from transmogrifai_tpu.types import feature_types as ft

    X, y = xy_reg
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = FeatureBuilder.OPVector("feats").from_column().as_predictor()
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y.astype(np.float64)),
        "feats": VectorColumn(ft.OPVector, X.astype(np.float64))})
    est = OpGBTRegressor(max_iter=10, max_depth=3,
                         min_instances_per_node=5).set_input(label, feats)
    model = est.fit(store)
    pred, _, _ = model.predict_arrays(X.astype(np.float64))
    assert 1.0 - (y - pred).var() / y.var() > 0.6


def test_selector_with_trees(xy_cls):
    """ModelSelector CV over an LR + RF + GBT mix picks a strong model."""
    from transmogrifai_tpu.features import FeatureBuilder
    from transmogrifai_tpu.columns import ColumnStore, VectorColumn, \
        column_from_values
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import \
        BinaryClassificationModelSelector

    X, y = xy_cls
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = FeatureBuilder.OPVector("feats").from_column().as_predictor()
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y.astype(np.float64)),
        "feats": VectorColumn(ft.OPVector, X.astype(np.float64))})
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2,
        families=[
            LogisticRegressionFamily(grid=[{"regParam": 0.01,
                                            "elasticNetParam": 0.0}],
                                     max_iter=16),
            RandomForestFamily(grid=[{"maxDepth": 4,
                                      "minInstancesPerNode": 5,
                                      "minInfoGain": 1e-3}], num_trees=5),
            GBTFamily(grid=[{"maxDepth": 3, "minInstancesPerNode": 5,
                             "minInfoGain": 1e-3}], max_iter=5),
        ]).set_input(label, feats)
    model = selector.fit(store)
    summ = model.selector_summary
    # XOR-ish label: trees must beat logistic regression
    assert summ.best_model_name in ("OpRandomForestClassifier",
                                    "OpGBTClassifier")
    assert summ.train_evaluation["AuROC"] > 0.9
    assert len(summ.validator_summary.results) == 3


def test_per_node_feature_subsampling(rng):
    """VERDICT r2 #6: RF candidate features are sampled per NODE (Spark
    featureSubsetStrategy parity), on by default. The per-node forest must
    differ structurally from the per-tree one (the masks really vary by
    node) while matching or beating its quality on correlated features."""
    import jax.numpy as jnp

    from transmogrifai_tpu.models import _treefit as TF

    n, F = 600, 12
    base = rng.normal(size=(n, 1))
    # correlated block: 6 near-copies of the signal + 6 noise columns
    X = np.concatenate([base + 0.05 * rng.normal(size=(n, 6)),
                        rng.normal(size=(n, 6))], axis=1)
    y = (base[:, 0] > 0).astype(float)
    ho = rng.normal(size=(400, 1))
    Xh = np.concatenate([ho + 0.05 * rng.normal(size=(400, 6)),
                         rng.normal(size=(400, 6))], axis=1)
    yh = (ho[:, 0] > 0).astype(float)

    kw = dict(task="classification", n_classes=2, n_trees=20, max_depth=4,
              n_bins=16, min_instances=jnp.asarray(1.0),
              min_info_gain=jnp.asarray(0.0),
              num_trees_used=jnp.asarray(20),
              subsample_rate=jnp.asarray(1.0), seed=11)
    p_node = TF.fit_forest(jnp.asarray(X), jnp.asarray(y),
                           jnp.ones((n,)), per_node_features=True, **kw)
    p_tree = TF.fit_forest(jnp.asarray(X), jnp.asarray(y),
                           jnp.ones((n,)), per_node_features=False, **kw)
    assert not np.array_equal(np.asarray(p_node["feat"]),
                              np.asarray(p_tree["feat"]))

    def acc(params):
        out = TF.predict_ensemble(params["feat"], params["thr"],
                                  params["leaf"], params["tree_w"],
                                  jnp.asarray(Xh), 4)
        pred = np.asarray(out).argmax(axis=1)
        return (pred == yh).mean()

    a_node, a_tree = acc(p_node), acc(p_tree)
    # quality parity bar: per-node must not lose on correlated features
    assert a_node >= a_tree - 0.02, (a_node, a_tree)
    # and per-node trees must use a wider feature set overall (diversity)
    used_node = len(np.unique(np.asarray(p_node["feat"])))
    used_tree = len(np.unique(np.asarray(p_tree["feat"])))
    assert used_node >= used_tree - 1, (used_node, used_tree)


def test_sibling_subtraction_exact_parity(monkeypatch):
    """The unrolled driver histograms only LEFT children and derives each
    right sibling as parent − left (LightGBM's subtraction trick). In
    f64 (the CPU test dtype) the subtraction is exact, so the grown
    trees must match the scan driver's full-histogram build EXACTLY,
    with and without the TMOG_SIBLING escape hatch."""
    import jax.numpy as jnp

    from transmogrifai_tpu.models import _treefit as TF

    rng = np.random.default_rng(5)
    n, F = 2500, 7
    X = jnp.asarray(rng.normal(size=(n, F)))
    y = jnp.asarray((rng.normal(size=n) + np.asarray(X)[:, 0] > 0)
                    .astype(np.float64))
    w = jnp.ones((n,))
    kw = dict(task="classification", n_classes=2, n_trees=5, max_depth=5,
              n_bins=16, min_instances=jnp.asarray(2.0),
              min_info_gain=jnp.asarray(0.001),
              num_trees_used=jnp.asarray(5.0),
              subsample_rate=jnp.asarray(1.0), seed=5)
    scan = TF.fit_forest(X, y, w, **kw)
    pre = TF.prepare_bins(X, 16, None)
    prebinned = (pre[0], pre[1], pre[2], False)
    monkeypatch.delenv("TMOG_SIBLING", raising=False)
    sib = TF.fit_forest(None, y, w, prebinned=prebinned, unroll=True, **kw)
    monkeypatch.setenv("TMOG_SIBLING", "0")
    nosib = TF.fit_forest(None, y, w, prebinned=prebinned, unroll=True,
                          **kw)
    for k in ("feat", "thr", "leaf", "train_node", "gain"):
        np.testing.assert_allclose(np.asarray(scan[k]), np.asarray(sib[k]),
                                   rtol=0, atol=1e-9)
        np.testing.assert_allclose(np.asarray(sib[k]),
                                   np.asarray(nosib[k]),
                                   rtol=0, atol=1e-9)
