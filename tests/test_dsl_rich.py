"""Rich* DSL long tail (RichMapFeature.scala:91-664,
RichTextFeature.scala:58-650): per-call vectorize overrides with map key
white/blacklists, smart text-map vectorization, label-aware bucketing,
language detection, text predicates."""
import numpy as np

from transmogrifai_tpu import FeatureBuilder, Workflow
from transmogrifai_tpu.columns import ColumnStore
from transmogrifai_tpu.types import feature_types as ft


def _train(store, *feats):
    model = (Workflow().set_input_store(store)
             .set_result_features(*feats).train())
    return model, model.transform(store)


def test_map_vectorize_key_lists():
    store = ColumnStore.from_dict({
        "m": (ft.RealMap, [{"a": 1.0, "b": 5.0, "leak": 9.0},
                           {"a": 2.0, "leak": 8.0}, {"b": 1.0}])})
    m = FeatureBuilder.RealMap("m").from_column().as_predictor()
    vec = m.vectorize(block_keys=["leak"])
    _, out = _train(store, vec)
    meta = out[vec.name].metadata
    groups = {c.grouping for c in meta.columns}
    assert groups == {"a", "b"}

    m2 = FeatureBuilder.RealMap("m").from_column().as_predictor()
    vec2 = m2.vectorize(allow_keys=["a"])
    _, out2 = _train(store, vec2)
    assert {c.grouping for c in out2[vec2.name].metadata.columns} == {"a"}


def test_textmap_smart_vectorize_routes_per_key():
    n = 60
    rows = [{"plan": ["free", "pro"][i % 2], "note": f"unique-{i}"}
            for i in range(n)]
    store = ColumnStore.from_dict({"m": (ft.TextMap, rows)})
    m = FeatureBuilder.TextMap("m").from_column().as_predictor()
    vec = m.smart_vectorize(max_cardinality=5, num_features=16,
                            min_support=1, top_k=10)
    _, out = _train(store, vec)
    meta = out[vec.name].metadata
    plan_cols = [c for c in meta.columns if c.grouping == "plan"]
    note_cols = [c for c in meta.columns if c.grouping == "note"]
    # plan pivoted (indicator per level), note hashed (num_features wide)
    assert any(c.indicator_value == "free" for c in plan_cols)
    assert len(note_cols) >= 16
    assert not any(c.indicator_value and c.indicator_value.startswith("unique")
                   for c in note_cols)


def test_auto_bucketize_map_key():
    rng = np.random.default_rng(0)
    x = rng.normal(size=200)
    y = (x > 0.3).astype(float)
    store = ColumnStore.from_dict({
        "m": (ft.RealMap, [{"k": float(v)} for v in x]),
        "y": (ft.RealNN, y.tolist())})
    yf = FeatureBuilder.RealNN("y").from_column().as_response()
    m = FeatureBuilder.RealMap("m").from_column().as_predictor()
    b = m.extract_key("k").auto_bucketize(yf)
    _, out = _train(store, b)
    mat = out[b.name].values
    assert mat.shape[0] == 200 and mat.shape[1] >= 2
    # the DT found a split near 0.3: bucket membership predicts y
    upper = mat[:, -2] if mat.shape[1] > 2 else mat[:, 1]
    assert abs(np.corrcoef(upper, y)[0, 1]) > 0.5


def test_text_predicates_and_language():
    store = ColumnStore.from_dict({
        "t": (ft.Text, ["la casa de la madre en la ciudad",
                        "the dog and the cat in the house", None]),
        "e": (ft.Email, ["ok@x.io", "not-an-email", None]),
        "u": (ft.URL, ["http://a.bc/c", "junk", None]),
        "s": (ft.Text, ["dog", "zebra", None]),
        "big": (ft.Text, ["the dog barks", "the cat meows", "x"]),
    })
    t = FeatureBuilder.Text("t").from_column().as_predictor()
    e = FeatureBuilder.Email("e").from_column().as_predictor()
    u = FeatureBuilder.URL("u").from_column().as_predictor()
    s = FeatureBuilder.Text("s").from_column().as_predictor()
    big = FeatureBuilder.Text("big").from_column().as_predictor()

    langs = t.detect_languages()
    ve = e.is_valid_email()
    vu = u.is_valid_url()
    sub = s.is_substring(big)
    _, out = _train(store, langs, ve, vu, sub)

    l0 = out[langs.name].get_raw(0)
    l1 = out[langs.name].get_raw(1)
    assert l0.get("es", 0) > l0.get("en", 0)
    assert l1.get("en", 0) > l1.get("es", 0)
    assert [out[ve.name].get_raw(i) for i in range(3)] == [True, False, None]
    assert [out[vu.name].get_raw(i) for i in range(3)] == [True, False, None]
    assert [out[sub.name].get_raw(i) for i in range(3)] == [True, False, None]


def test_mapprep_example_end_to_end():
    """VERDICT r2 #7 'done' bar: a dataprep-style example exercises
    map-typed features through the new DSL end-to-end."""
    import os
    import sys
    examples = os.path.join(os.path.dirname(__file__), os.pardir, "examples")
    sys.path.insert(0, examples)
    try:
        from mapprep import run
    finally:
        sys.path.remove(examples)
    out = run(n=800, seed=3)
    assert not out["blocked_cols"], "blacklisted key leaked into the vector"
    assert out["metrics"]["AuPR"] > 0.7


def test_dsl_defaults_match_estimator_defaults():
    """VERDICT r3 #8: DSL entry points must forward estimator defaults
    untouched — a round-3 `word2vec(dim=32)` default in dsl.py silently
    diverged from OpWord2Vec's Spark-parity dim=100/window=5."""
    import inspect

    from transmogrifai_tpu.ops.topics import OpLDA, OpWord2Vec

    t = FeatureBuilder.TextList("t").from_column().as_predictor()
    w2v = t.word2vec()
    stage = w2v.origin_stage
    sig = inspect.signature(OpWord2Vec.__init__)
    assert stage.dim == sig.parameters["dim"].default == 100
    assert stage.window == sig.parameters["window"].default == 5

    t2 = FeatureBuilder.TextList("t").from_column().as_predictor()
    lda = t2.lda()
    assert lda.origin_stage.n_topics == \
        inspect.signature(OpLDA.__init__).parameters["n_topics"].default


def test_rich_list_tf_tfidf_ngram_stopwords():
    """RichListFeature long tail (RichListFeature.scala:59-186): tf /
    tfidf / ngram / removeStopWords through the DSL."""
    docs = [["the", "cat", "sat"], ["the", "dog", "sat", "still"],
            ["a", "cat"], []]
    store = ColumnStore.from_dict({"t": (ft.TextList, docs)})

    t = FeatureBuilder.TextList("t").from_column().as_predictor()
    cleaned = t.remove_stop_words()
    grams = t.ngram(2)
    tfv = t.tf(num_terms=32)
    tfidf = t.tfidf(num_terms=32)
    model, out = _train(store, cleaned, grams, tfv, tfidf)

    assert out[cleaned.name].get_raw(0) == ["cat", "sat"]  # "the" dropped
    assert out[grams.name].get_raw(0) == ["the cat", "cat sat"]
    assert out[grams.name].get_raw(3) == []
    tf_row0 = np.asarray(out[tfv.name].values[0])
    assert tf_row0.sum() == 3.0                  # one bucket hit per token
    # tf-idf: a term present in EVERY doc ("sat" rows 0,1) scales below a
    # rarer term's weight; all-zero row stays zero
    assert np.asarray(out[tfidf.name].values[3]).sum() == 0.0


def test_rich_set_jaccard_and_pivot():
    """RichSetFeature (RichSetFeature.scala:65-142): MultiPickList pivot
    via vectorize + jaccardSimilarity."""
    a = [{"x", "y"}, {"x"}, set()]
    b = [{"x", "y"}, {"z"}, set()]
    store = ColumnStore.from_dict({"a": (ft.MultiPickList, a),
                                   "b": (ft.MultiPickList, b)})
    fa = FeatureBuilder.MultiPickList("a").from_column().as_predictor()
    fb = FeatureBuilder.MultiPickList("b").from_column().as_predictor()
    sim = fa.jaccard_similarity(fb)
    vec = fa.vectorize(top_k=5, min_support=1)
    model, out = _train(store, sim, vec)
    got = [float(out[sim.name].get_raw(i)) for i in range(3)]
    assert got[0] == 1.0 and got[1] == 0.0 and got[2] == 1.0
    cols = out[vec.name].metadata.columns
    assert any(c.indicator_value == "x" for c in cols)


def test_rich_numeric_unary_math_and_scaling():
    """RichNumericFeature unary tail (abs/ceil/floor/round/exp/log/sqrt/
    power) + scale/descale (ScalerTransformer.scala)."""
    store = ColumnStore.from_dict({
        "x": (ft.Real, [4.0, -2.25, None, 0.0])})
    x = FeatureBuilder.Real("x").from_column().as_predictor()
    feats = [x.abs(), x.ceil(), x.floor(), x.round_to(1), x.sqrt(),
             x.log(), x.power(2.0), x.exp()]
    sc = x.scaled(slope=2.0, intercept=1.0)
    de = sc.descaled(sc)      # a value in scaled space, inverted back
    model, out = _train(store, *feats, sc, de)
    g = lambda f, i: out[f.name].get_raw(i)
    assert g(feats[0], 1) == 2.25            # abs
    assert g(feats[1], 1) == -2.0            # ceil
    assert g(feats[2], 1) == -3.0            # floor
    assert g(feats[3], 1) == -2.2            # round
    assert g(feats[4], 0) == 2.0             # sqrt(4)
    assert g(feats[4], 1) is None            # sqrt(-2.25) -> null
    assert abs(g(feats[5], 0) - np.log(4.0)) < 1e-12
    assert g(feats[5], 3) is None            # log(0) -> null
    assert g(feats[6], 1) == 2.25 ** 2       # power
    assert g(feats[0], 2) is None            # null propagates
    assert g(sc, 0) == 9.0                   # 2x+1
    assert g(de, 0) == 4.0                   # descale round-trips


def test_rich_numeric_isotonic_calibration():
    rng = np.random.default_rng(0)
    n = 300
    score = np.sort(rng.random(n))
    y = (rng.random(n) < score).astype(float)   # monotone in score
    store = ColumnStore.from_dict({
        "y": (ft.RealNN, y.tolist()), "s": (ft.Real, score.tolist())})
    ybl = FeatureBuilder.RealNN("y").from_column().as_response()
    s = FeatureBuilder.Real("s").from_column().as_predictor()
    cal = s.to_isotonic_calibrated(ybl)
    model, out = _train(store, cal)
    vals = np.asarray([out[cal.name].get_raw(i) for i in range(n)], float)
    assert np.all(np.diff(vals) >= -1e-9)       # monotone output
    assert 0.0 <= vals.min() and vals.max() <= 1.0


def test_location_vectorize_pivot():
    """RichLocationFeature.vectorize: location-text types pivot top-K +
    OTHER (+ null)."""
    vals = ["CA", "NY", "CA", None, "TX", "CA", "NY", "WA"]
    store = ColumnStore.from_dict({"st": (ft.State, vals)})
    st = FeatureBuilder.State("st").from_column().as_predictor()
    vec = st.vectorize_location(top_k=2, min_support=1)
    _, out = _train(store, vec)
    meta = out[vec.name].metadata
    indicators = [c.indicator_value for c in meta.columns]
    assert "CA" in indicators and "NY" in indicators
    assert "TX" not in indicators            # beyond top_k → OTHER
    mat = out[vec.name].values
    assert mat.shape == (len(vals), len(meta.columns))
    # row 3 is null → null-indicator column set
    null_idx = [i for i, c in enumerate(meta.columns)
                if c.indicator_value == "NullIndicatorValue"]
    assert mat[3, null_idx[0]] == 1.0


def test_email_url_phone_map_surfaces():
    rows_email = [{"w": "a@gmail.com", "h": "b@yahoo.com"},
                  {"w": "c@gmail.com"}, {"h": "not-an-email"}]
    rows_url = [{"s": "https://example.com/x", "b": "nope"},
                {"s": "http://foo.org"}, {}]
    rows_phone = [{"m": "(555) 123-4567", "o": "12"},
                  {"m": "+44 7700 900123"}, {}]
    store = ColumnStore.from_dict({
        "em": (ft.EmailMap, rows_email),
        "um": (ft.URLMap, rows_url),
        "pm": (ft.PhoneMap, rows_phone)})
    em = FeatureBuilder.EmailMap("em").from_column().as_predictor()
    um = FeatureBuilder.URLMap("um").from_column().as_predictor()
    pm = FeatureBuilder.PhoneMap("pm").from_column().as_predictor()
    dom = em.to_email_domain_map()
    ud = um.to_url_domain_map()
    pv = pm.is_valid_phone_map()
    _, out = _train(store, dom, ud, pv)
    assert out[dom.name].get_raw(0) == {"w": "gmail.com", "h": "yahoo.com"}
    assert out[dom.name].get_raw(2) == {}    # invalid email dropped
    assert out[ud.name].get_raw(0) == {"s": "example.com"}  # invalid dropped
    assert out[ud.name].get_raw(1) == {"s": "foo.org"}
    assert out[pv.name].get_raw(0) == {"m": True, "o": False}
    assert out[pv.name].get_raw(1) == {"m": True}


def test_prediction_tupled():
    """RichPredictionFeature.tupled: Prediction → 3 plain features."""
    from transmogrifai_tpu.models import BinaryClassificationModelSelector
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    rng = np.random.default_rng(0)
    x = rng.normal(size=120)
    y = (x > 0).astype(float)
    store = ColumnStore.from_dict({
        "y": (ft.RealNN, y.tolist()), "x": (ft.Real, x.tolist())})
    ybl = FeatureBuilder.RealNN("y").from_column().as_response()
    xf = FeatureBuilder.Real("x").from_column().as_predictor()
    vec = xf.vectorize()
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, validation_metric="AuPR",
        families=[LogisticRegressionFamily(
            grid=[{"regParam": 0.01, "elasticNetParam": 0.0}])], seed=3)
    pred = ybl.transform_with(sel, vec)
    p, raw, prob = pred.tupled()
    _, out = _train(store, p, raw, prob)
    n = len(y)
    pv = np.asarray([out[p.name].get_raw(i) for i in range(n)], float)
    assert set(np.unique(pv)) <= {0.0, 1.0}
    probm = out[prob.name].values
    assert probm.shape == (n, 2)
    np.testing.assert_allclose(probm.sum(axis=1), 1.0, atol=1e-5)
    rawm = out[raw.name].values
    assert rawm.shape == (n, 2)


def test_rich_feature_value_surface():
    """RichFeature residue: replaceWith / filter / filterNot / collect /
    exists / occurs (RichFeature.scala:61-205)."""
    vals = ["a", "b", None, "a", "c"]
    store = ColumnStore.from_dict({"t": (ft.PickList, vals)})
    t = FeatureBuilder.PickList("t").from_column().as_predictor()
    rep = t.replace_with("a", "z")
    fil = t.filter_values(lambda v: v in ("a", "b"), "OTHER")
    fnot = t.filter_not(lambda v: v == "a", "X")
    col = t.collect(lambda v: v.upper() if v == "b" else None, "D")
    ex = t.exists(lambda v: v == "c")
    oc = t.occurs()
    _, out = _train(store, rep, fil, fnot, col, ex, oc)
    g = lambda f: [out[f.name].get_raw(i) for i in range(len(vals))]
    assert g(rep) == ["z", "b", None, "z", "c"]
    assert g(fil) == ["a", "b", "OTHER", "a", "OTHER"]
    # None: p(None) is False, so filter_not KEEPS it (matches the
    # reference where the predicate sees the empty value)
    assert g(fnot) == ["X", "b", None, "X", "c"]
    assert g(col) == ["D", "B", "D", "D", "D"]
    assert g(ex) == [0.0, 0.0, 0.0, 0.0, 1.0]
    assert g(oc) == [1.0, 1.0, 0.0, 1.0, 1.0]


def test_drop_indices_by():
    """RichVectorFeature.dropIndicesBy: metadata-predicate column drop."""
    vals = ["x", "y", "x", None]
    store = ColumnStore.from_dict({"p": (ft.PickList, vals)})
    p = FeatureBuilder.PickList("p").from_column().as_predictor()
    vec = p.pivot(top_k=5, min_support=1)
    dropped = vec.drop_indices_by(
        lambda cm: cm.indicator_value == "NullIndicatorValue")
    _, out = _train(store, vec, dropped)
    full = out[vec.name]
    slim = out[dropped.name]
    assert slim.values.shape[1] == full.values.shape[1] - 1
    assert not any(c.indicator_value == "NullIndicatorValue"
                   for c in slim.metadata.columns)


def test_date_list_conversions_and_value_op_io(tmp_path):
    """to_date_list/to_date_time_list (RichDateFeature :54,:124) + the
    value-op surface survives model save/load (fn_io round-trip)."""
    from transmogrifai_tpu.model_io import (load_workflow_model,
                                            save_workflow_model)
    ts = [1471046600000, None, 1471046700000]
    store = ColumnStore.from_dict({
        "d": (ft.Date, ts), "t": (ft.PickList, ["a", "b", None])})
    d = FeatureBuilder.Date("d").from_column().as_predictor()
    t = FeatureBuilder.PickList("t").from_column().as_predictor()
    dl = d.to_date_list()
    oc = t.occurs()
    ex = t.exists(lambda v: v == "b")
    model, out = _train(store, dl, oc, ex)
    assert out[dl.name].get_raw(0) == [ts[0]]
    assert out[dl.name].get_raw(1) == []
    save_workflow_model(model, str(tmp_path / "m"))
    loaded = load_workflow_model(str(tmp_path / "m"))
    out2 = loaded.transform(store)
    assert [out2[oc.name].get_raw(i) for i in range(3)] == [1.0, 1.0, 0.0]
    assert [out2[ex.name].get_raw(i) for i in range(3)] == [0.0, 1.0, 0.0]
    assert out2[dl.name].get_raw(2) == [ts[2]]
