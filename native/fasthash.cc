// Native host-side kernels for transmogrifai_tpu.
//
// The TPU owns the model math; the host's hot loops are string work —
// hashing-trick token hashing above all (ops/hashing.py). The pure-Python
// murmur3 fallback is ~1µs/token; this batch kernel hashes a whole token
// column per call through one ctypes crossing.
//
// Build: `make -C native` (or the lazy auto-build in ops/hashing.py).
// ABI: plain C functions, numpy arrays passed as raw pointers.

#include <cstdint>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

// MurmurHash3 x86 32-bit (public domain algorithm, Austin Appleby) —
// bit-exact with ops/hashing.py murmur3_32 and the reference's
// scala.util.hashing.MurmurHash3 usage.
inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

uint32_t murmur3_32(const uint8_t* data, int64_t len, uint32_t seed) {
  const uint32_t c1 = 0xcc9e2d51u;
  const uint32_t c2 = 0x1b873593u;
  uint32_t h = seed;
  const int64_t nblocks = len / 4;

  for (int64_t i = 0; i < nblocks; i++) {
    uint32_t k;
    std::memcpy(&k, data + 4 * i, 4);  // little-endian hosts only
    k *= c1;
    k = rotl32(k, 15);
    k *= c2;
    h ^= k;
    h = rotl32(h, 13);
    h = h * 5 + 0xe6546b64u;
  }

  const uint8_t* tail = data + nblocks * 4;
  uint32_t k = 0;
  switch (len & 3) {
    case 3: k ^= static_cast<uint32_t>(tail[2]) << 16; [[fallthrough]];
    case 2: k ^= static_cast<uint32_t>(tail[1]) << 8;  [[fallthrough]];
    case 1:
      k ^= tail[0];
      k *= c1;
      k = rotl32(k, 15);
      k *= c2;
      h ^= k;
  }

  h ^= static_cast<uint32_t>(len);
  h ^= h >> 16;
  h *= 0x85ebca6bu;
  h ^= h >> 13;
  h *= 0xc2b2ae35u;
  h ^= h >> 16;
  return h;
}

}  // namespace

extern "C" {

// Hash n strings packed into one blob: string i spans
// blob[offsets[i]..offsets[i+1]). Writes n uint32 hashes into out.
void murmur3_batch(const char* blob, const int64_t* offsets, int64_t n,
                   uint32_t seed, uint32_t* out) {
  const uint8_t* base = reinterpret_cast<const uint8_t*>(blob);
  for (int64_t i = 0; i < n; i++) {
    out[i] = murmur3_32(base + offsets[i], offsets[i + 1] - offsets[i],
                        seed);
  }
}

// Hash n strings and fold each into a bucket in [0, num_features),
// fusing the modulo into the same pass (saves one numpy round trip).
void murmur3_bucket_batch(const char* blob, const int64_t* offsets,
                          int64_t n, uint32_t seed, uint32_t num_features,
                          int64_t* out) {
  const uint8_t* base = reinterpret_cast<const uint8_t*>(blob);
  for (int64_t i = 0; i < n; i++) {
    uint32_t h = murmur3_32(base + offsets[i], offsets[i + 1] - offsets[i],
                            seed);
    out[i] = static_cast<int64_t>(h % num_features);
  }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Fused tokenize + hash + count scatter (ops/smart_text.py hashing path).
//
// The Python path at 300k rows spent ~10 s per transform in re.findall,
// list plumbing and object-array uniques before the first hash; this
// kernel streams each string once: maximal runs of [A-Za-z0-9_']
// (the ASCII fast path of the tokenizer's [\w']+ with lower()) are
// lowercased in place, murmur3-hashed and scattered straight into the
// caller's [n, row_stride] f32 matrix. Any string containing a byte
// >= 0x80 is flagged and left untouched so the caller can route just
// those rows through the exact (unicode-aware) Python tokenizer.
// ---------------------------------------------------------------------------

namespace {

inline bool token_byte(uint8_t c) {
  return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
         (c >= '0' && c <= '9') || c == '_' || c == '\'';
}

void tokenize_rows(const uint8_t* base, const int64_t* offsets,
                   int64_t row_begin, int64_t row_end, uint32_t seed,
                   uint32_t num_features, int32_t min_token_len,
                   int32_t binary_freq, float* out, int64_t row_stride,
                   int64_t col_offset, uint8_t* flags) {
  // token scratch: lowercased bytes (grown on demand for long tokens);
  // std::vector, not basic_string<uint8_t> — char_traits<uint8_t> is a
  // non-standard specialization libc++ rejects outright
  std::vector<uint8_t> tok;
  tok.reserve(64);
  for (int64_t i = row_begin; i < row_end; i++) {
    const uint8_t* s = base + offsets[i];
    const int64_t len = offsets[i + 1] - offsets[i];
    bool ascii = true;
    for (int64_t j = 0; j < len; j++) {
      if (s[j] >= 0x80) { ascii = false; break; }
    }
    if (!ascii) {
      flags[i] = 1;  // caller re-does this row in Python (exact \w)
      continue;
    }
    float* row = out + i * row_stride + col_offset;
    int64_t j = 0;
    while (j < len) {
      while (j < len && !token_byte(s[j])) j++;
      int64_t start = j;
      while (j < len && token_byte(s[j])) j++;
      if (j - start >= min_token_len) {
        tok.clear();
        for (int64_t k = start; k < j; k++) {
          uint8_t c = s[k];
          tok.push_back(c >= 'A' && c <= 'Z' ? c + 32 : c);
        }
        uint32_t h = murmur3_32(tok.data(),
                                static_cast<int64_t>(tok.size()), seed);
        uint32_t b = h % num_features;
        if (binary_freq) {
          row[b] = 1.0f;
        } else {
          row[b] += 1.0f;
        }
      }
    }
  }
}

}  // namespace

extern "C" {

// See tokenize_rows. Threads split the row range; each writes disjoint
// output rows, so the pass is race-free. n_threads <= 1 runs inline.
void tokenized_hash_counts(const char* blob, const int64_t* offsets,
                           int64_t n, uint32_t seed, uint32_t num_features,
                           int32_t min_token_len, int32_t binary_freq,
                           float* out, int64_t row_stride,
                           int64_t col_offset, uint8_t* flags,
                           int32_t n_threads) {
  const uint8_t* base = reinterpret_cast<const uint8_t*>(blob);
  if (n_threads <= 1 || n < 4096) {
    tokenize_rows(base, offsets, 0, n, seed, num_features, min_token_len,
                  binary_freq, out, row_stride, col_offset, flags);
    return;
  }
  std::vector<std::thread> workers;
  workers.reserve(n_threads);
  const int64_t chunk = (n + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; t++) {
    const int64_t lo = t * chunk;
    const int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    workers.emplace_back(tokenize_rows, base, offsets, lo, hi, seed,
                         num_features, min_token_len, binary_freq, out,
                         row_stride, col_offset, flags);
  }
  for (auto& w : workers) w.join();
}

}  // extern "C"
