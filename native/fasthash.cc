// Native host-side kernels for transmogrifai_tpu.
//
// The TPU owns the model math; the host's hot loops are string work —
// hashing-trick token hashing above all (ops/hashing.py). The pure-Python
// murmur3 fallback is ~1µs/token; this batch kernel hashes a whole token
// column per call through one ctypes crossing.
//
// Build: `make -C native` (or the lazy auto-build in ops/hashing.py).
// ABI: plain C functions, numpy arrays passed as raw pointers.

#include <cstdint>
#include <cstring>

namespace {

// MurmurHash3 x86 32-bit (public domain algorithm, Austin Appleby) —
// bit-exact with ops/hashing.py murmur3_32 and the reference's
// scala.util.hashing.MurmurHash3 usage.
inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

uint32_t murmur3_32(const uint8_t* data, int64_t len, uint32_t seed) {
  const uint32_t c1 = 0xcc9e2d51u;
  const uint32_t c2 = 0x1b873593u;
  uint32_t h = seed;
  const int64_t nblocks = len / 4;

  for (int64_t i = 0; i < nblocks; i++) {
    uint32_t k;
    std::memcpy(&k, data + 4 * i, 4);  // little-endian hosts only
    k *= c1;
    k = rotl32(k, 15);
    k *= c2;
    h ^= k;
    h = rotl32(h, 13);
    h = h * 5 + 0xe6546b64u;
  }

  const uint8_t* tail = data + nblocks * 4;
  uint32_t k = 0;
  switch (len & 3) {
    case 3: k ^= static_cast<uint32_t>(tail[2]) << 16; [[fallthrough]];
    case 2: k ^= static_cast<uint32_t>(tail[1]) << 8;  [[fallthrough]];
    case 1:
      k ^= tail[0];
      k *= c1;
      k = rotl32(k, 15);
      k *= c2;
      h ^= k;
  }

  h ^= static_cast<uint32_t>(len);
  h ^= h >> 16;
  h *= 0x85ebca6bu;
  h ^= h >> 13;
  h *= 0xc2b2ae35u;
  h ^= h >> 16;
  return h;
}

}  // namespace

extern "C" {

// Hash n strings packed into one blob: string i spans
// blob[offsets[i]..offsets[i+1]). Writes n uint32 hashes into out.
void murmur3_batch(const char* blob, const int64_t* offsets, int64_t n,
                   uint32_t seed, uint32_t* out) {
  const uint8_t* base = reinterpret_cast<const uint8_t*>(blob);
  for (int64_t i = 0; i < n; i++) {
    out[i] = murmur3_32(base + offsets[i], offsets[i + 1] - offsets[i],
                        seed);
  }
}

// Hash n strings and fold each into a bucket in [0, num_features),
// fusing the modulo into the same pass (saves one numpy round trip).
void murmur3_bucket_batch(const char* blob, const int64_t* offsets,
                          int64_t n, uint32_t seed, uint32_t num_features,
                          int64_t* out) {
  const uint8_t* base = reinterpret_cast<const uint8_t*>(blob);
  for (int64_t i = 0; i < n; i++) {
    uint32_t h = murmur3_32(base + offsets[i], offsets[i + 1] - offsets[i],
                            seed);
    out[i] = static_cast<int64_t>(h % num_features);
  }
}

}  // extern "C"
